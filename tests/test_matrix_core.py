"""Core distributed-type tests.

Mirrors the reference's DistributedMatrixSuite pattern
(src/test/scala/.../DistributedMatrixSuite.scala): tiny fixtures, run the
distributed op, ``to_numpy()``, compare to a hand-computed local oracle.
"""

import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu.matrix.dense import DenseVecMatrix
from marlin_tpu.matrix.block import BlockMatrix
from marlin_tpu.matrix.vector import DistributedVector

# The reference's 4x4 fixture rows (DistributedMatrixSuite.scala:15-32 style).
A4 = np.array(
    [
        [1.0, 2.0, 3.0, 4.0],
        [2.0, 3.0, 4.0, 5.0],
        [3.0, 4.0, 5.0, 6.0],
        [4.0, 5.0, 6.0, 7.0],
    ]
)
B4 = np.array(
    [
        [1.0, 0.0, 2.0, 0.0],
        [0.0, 1.0, 0.0, 2.0],
        [2.0, 0.0, 1.0, 0.0],
        [0.0, 2.0, 0.0, 1.0],
    ]
)


def dvm(arr):
    return DenseVecMatrix(arr)


def blk(arr, r=2, c=2):
    return BlockMatrix(arr, blks_by_row=r, blks_by_col=c)


class TestMetadata:
    def test_size_inference(self):
        m = dvm(A4)
        assert m.num_rows == 4 and m.num_cols == 4
        assert m.elements_count() == 16

    def test_empty_error_contract(self):
        # Reference: sys.error on an empty RDD (suite :53).
        with pytest.raises(ValueError):
            DenseVecMatrix(np.zeros((0, 3)))
        with pytest.raises(ValueError):
            DistributedVector(np.zeros((0,)))

    def test_from_rows(self):
        m = DenseVecMatrix.from_rows([(0, A4[0]), (2, A4[2]), (1, A4[1]), (3, A4[3])])
        np.testing.assert_allclose(m.to_numpy(), A4)


class TestElementwise:
    @pytest.mark.parametrize("make", [dvm, blk])
    def test_add_subtract(self, make):
        m = make(A4)
        np.testing.assert_allclose(m.add(make(B4)).to_numpy(), A4 + B4)
        np.testing.assert_allclose(m.subtract(make(B4)).to_numpy(), A4 - B4)
        np.testing.assert_allclose(m.add(2.5).to_numpy(), A4 + 2.5)
        np.testing.assert_allclose(m.subtract(1.5).to_numpy(), A4 - 1.5)

    @pytest.mark.parametrize("make", [dvm, blk])
    def test_scalar_ops(self, make):
        m = make(A4)
        np.testing.assert_allclose(m.multiply(3.0).to_numpy(), A4 * 3)
        np.testing.assert_allclose(m.divide(2.0).to_numpy(), A4 / 2)
        np.testing.assert_allclose(m.divide_by(2.0).to_numpy(), 2 / A4)
        np.testing.assert_allclose(m.subtract_by(10.0).to_numpy(), 10 - A4)

    def test_element_multiply(self):
        np.testing.assert_allclose(
            blk(A4).element_multiply(blk(B4)).to_numpy(), A4 * B4
        )

    def test_shape_mismatch_raises(self):
        with pytest.raises(ValueError):
            dvm(A4).add(dvm(A4[:3]))


class TestReductions:
    def test_sum(self):
        assert dvm(A4).sum() == pytest.approx(A4.sum())
        assert blk(A4).sum() == pytest.approx(A4.sum())

    def test_dot_product_all_pairings(self):
        # All 4 type pairings (suite :326).
        expected = (A4 * B4).sum()
        for left in (dvm, blk):
            for right in (dvm, blk):
                assert left(A4).dot_product(right(B4)) == pytest.approx(expected)

    def test_norms(self):
        m = dvm(A4)
        assert m.norm("1") == pytest.approx(np.abs(A4).sum(axis=0).max())
        assert m.norm("inf") == pytest.approx(np.abs(A4).sum(axis=1).max())
        with pytest.raises(ValueError):
            m.norm("fro")


class TestStructure:
    @pytest.mark.parametrize("make", [dvm, blk])
    def test_transpose(self, make):
        np.testing.assert_allclose(make(A4).transpose().to_numpy(), A4.T)

    def test_c_bind(self):
        np.testing.assert_allclose(
            dvm(A4).c_bind(dvm(B4)).to_numpy(), np.hstack([A4, B4])
        )
        with pytest.raises(ValueError):
            dvm(A4).c_bind(dvm(B4[:2]))

    def test_slicing_inclusive(self):
        # Reference slicing is inclusive on both ends (DenseVecMatrix.scala:928).
        m = dvm(A4)
        np.testing.assert_allclose(m.slice_by_row(1, 2).to_numpy(), A4[1:3])
        np.testing.assert_allclose(m.slice_by_column(0, 1).to_numpy(), A4[:, 0:2])
        np.testing.assert_allclose(
            m.get_sub_matrix(1, 3, 2, 3).to_numpy(), A4[1:4, 2:4]
        )
        with pytest.raises(ValueError):
            m.slice_by_row(2, 4)

    def test_row_exchange(self):
        m = dvm(A4).row_exchange(0, 3)
        expected = A4.copy()
        expected[[0, 3]] = expected[[3, 0]]
        np.testing.assert_allclose(m.to_numpy(), expected)
        # Indices into the pad region must be rejected, not silently corrupt.
        with pytest.raises(ValueError):
            dvm(A4).row_exchange(1, 5)

    def test_block_transpose_swaps_grid(self):
        m = BlockMatrix(np.arange(35.0).reshape(5, 7), blks_by_row=2, blks_by_col=3)
        t = m.transpose()
        assert (t.blks_by_row, t.blks_by_col) == (3, 2)
        np.testing.assert_allclose(t.to_numpy(), np.arange(35.0).reshape(5, 7).T)

    def test_repeat(self):
        from marlin_tpu.utils.io import repeat_by_column, repeat_by_row

        np.testing.assert_allclose(
            repeat_by_row(dvm(A4), 2).to_numpy(), np.tile(A4, (2, 1))
        )
        np.testing.assert_allclose(
            repeat_by_column(dvm(A4), 3).to_numpy(), np.tile(A4, (1, 3))
        )


class TestConversions:
    def test_dense_block_roundtrip(self):
        m = dvm(A4).to_block_matrix(2, 2)
        assert isinstance(m, BlockMatrix)
        assert (m.blks_by_row, m.blks_by_col) == (2, 2)
        back = m.to_dense_vec_matrix()
        np.testing.assert_allclose(back.to_numpy(), A4)

    def test_block_regrid(self):
        m = blk(A4, 2, 2).to_block_matrix(4, 1)
        assert (m.blks_by_row, m.blks_by_col) == (4, 1)
        np.testing.assert_allclose(m.to_numpy(), A4)

    def test_block_extents_uneven(self):
        m = BlockMatrix(np.arange(35.0).reshape(5, 7), blks_by_row=2, blks_by_col=3)
        # Edge blocks are smaller (RandomRDD.scala:196-218 edge-dim logic).
        assert m.block_extent(1, 2) == (3, 5, 6, 7)
        np.testing.assert_allclose(
            np.asarray(m.get_block(1, 2)),
            np.arange(35.0).reshape(5, 7)[3:5, 6:7],
        )


class TestVector:
    def test_metadata_and_to_numpy(self):
        v = DistributedVector(np.arange(10.0))
        assert v.length == 10
        np.testing.assert_allclose(v.to_numpy(), np.arange(10.0))

    def test_subtract_and_transpose(self):
        a = DistributedVector(np.arange(6.0))
        b = DistributedVector(np.ones(6))
        np.testing.assert_allclose(a.substract(b).to_numpy(), np.arange(6.0) - 1)
        assert a.column_major and not a.transpose().column_major

    def test_inner_outer_product(self):
        # BLAS1 inner/outer products (suite :390).
        x = np.arange(1.0, 5.0)
        y = np.arange(2.0, 6.0)
        col = DistributedVector(x, column_major=True)
        row = DistributedVector(y, column_major=False)
        outer = col.multiply_vector(row, mode="dist")
        assert isinstance(outer, BlockMatrix)
        np.testing.assert_allclose(outer.to_numpy(), np.outer(x, y))
        np.testing.assert_allclose(col.multiply_vector(row, mode="local"), np.outer(x, y))
        inner = row.multiply_vector(col)
        assert inner == pytest.approx(x @ y)
        with pytest.raises(ValueError):
            col.multiply_vector(col)

    def test_rechunk_plan(self):
        from marlin_tpu.utils.split import reblock_plan

        plan = reblock_plan([0, 3, 7, 10], 4)
        # Copies must tile the whole extent exactly once.
        covered = sorted(
            (d[2] * 4 + d[3], d[2] * 4 + d[3] + d[4]) for d in plan
        )
        assert covered[0][0] == 0 and covered[-1][1] == 10
        total = sum(d[4] for d in plan)
        assert total == 10


class TestReductionAccumulators:
    def test_bf16_sum_accumulates_f32(self, rng):
        # 40k bf16 ones sum exactly to 40960 only with a wide accumulator
        # (bf16 integer representability ends at 256; a bf16-carried sum
        # saturates far below the true value).
        import jax.numpy as jnp

        a = DenseVecMatrix(jnp.ones((160, 256), jnp.bfloat16))
        assert a.sum() == 160 * 256
        b = DenseVecMatrix(jnp.ones((160, 256), jnp.bfloat16))
        assert a.dot_product(b) == 160 * 256
        assert a.norm("1") == 160
        assert a.norm("inf") == 256

    def test_bf16_vector_dot(self):
        import jax.numpy as jnp
        from marlin_tpu.matrix.vector import DistributedVector

        v = DistributedVector(jnp.ones((4096,), jnp.bfloat16))
        assert v.dot(v) == 4096
