"""Weight-only int8 decode (models/quant.py).

Oracle pattern: the int8 decode must compute exactly the function the
DEQUANTIZED float params compute (same graph, the convert/scale fused into
the dots), so parity is tested against ``dequantize_params`` — tight
tolerances, not 'close enough to the unquantized model'. Accuracy vs the
float masters is a separate, looser check. The streaming win (the point:
decode's HBM roofline denominator) is asserted structurally via the cost
harness: argument bytes drop ~4x and XLA's accessed-bytes follow."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from marlin_tpu.models import (TransformerConfig, dequantize_params,
                               generate, init_kv_cache, init_params,
                               loss_fn, prefill, quantize_params_int8)
from marlin_tpu.models import transformer as tr
from marlin_tpu.utils import cost_model as cm


def _cfg(**kw):
    base = dict(vocab=96, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                max_len=48)
    base.update(kw)
    return TransformerConfig(**base)


class TestQuantization:
    def test_roundtrip_error_bounded_by_half_step(self):
        p = init_params(_cfg(), seed=0)
        q = quantize_params_int8(p)
        d = dequantize_params(q)
        w, wq = p["blocks"][0]["wqkv"], q["blocks"][0]["wqkv"]
        assert wq["q8"].dtype == jnp.int8
        # Symmetric rounding: |w - q*s| <= s/2 per element, per channel.
        err = np.abs(np.asarray(w) - np.asarray(d["blocks"][0]["wqkv"]))
        assert np.all(err <= 0.5 * np.asarray(wq["s8"]) + 1e-8)

    def test_idempotent_and_moe_banks_stay_float(self):
        p = init_params(_cfg(n_experts=4), seed=1)
        q = quantize_params_int8(p)
        assert quantize_params_int8(q) is q
        assert q["blocks"][0]["w1"].ndim == 3  # expert bank untouched
        assert isinstance(q["blocks"][0]["wqkv"], dict)

    def test_zero_channel_survives(self):
        p = init_params(_cfg(), seed=0)
        p["blocks"][0]["wo"] = p["blocks"][0]["wo"].at[:, 3].set(0.0)
        d = dequantize_params(quantize_params_int8(p))
        assert np.all(np.isfinite(np.asarray(d["blocks"][0]["wo"])))
        assert np.all(np.asarray(d["blocks"][0]["wo"])[:, 3] == 0.0)


class TestDecodeParity:
    @pytest.mark.parametrize("kw", [
        {},
        {"rope": True, "n_kv_heads": 1, "window": 16},
        {"dtype": "bfloat16"},
    ])
    def test_decode_matches_dequantized_oracle(self, kw):
        cfg = _cfg(**kw)
        p = init_params(cfg, seed=2)
        q = quantize_params_int8(p)
        d = dequantize_params(q)
        b = 2
        tok = jnp.asarray([[5], [7]], jnp.int32)[:, 0]
        cache_q = init_kv_cache(cfg, b, dtype=jnp.dtype(cfg.dtype))
        cache_d = init_kv_cache(cfg, b, dtype=jnp.dtype(cfg.dtype))
        lq, _ = tr.decode_step(q, cache_q, tok, 0, cfg)
        ld, _ = tr.decode_step(d, cache_d, tok, 0, cfg)
        # Same function, same compute dtype — only op-ordering noise (the
        # readout applies the scale post-matmul on the int8 path).
        lqf = np.asarray(lq, np.float32)
        ldf = np.asarray(ld, np.float32)
        tol = 2e-2 if cfg.dtype == "bfloat16" else 2e-5
        np.testing.assert_allclose(lqf, ldf, rtol=tol,
                                   atol=tol * np.abs(ldf).max())

    def test_generate_end_to_end_and_close_to_master(self):
        cfg = _cfg()
        p = init_params(cfg, seed=3)
        q = quantize_params_int8(p)
        prompt = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab, (2, 8)),
            jnp.int32)
        out_q = generate(q, prompt, 6, cfg)
        assert out_q.shape == (2, 6) and out_q.dtype == jnp.int32
        assert int(jnp.min(out_q)) >= 0 and int(jnp.max(out_q)) < cfg.vocab
        # Greedy generation from the dequantized oracle matches exactly.
        out_d = generate(dequantize_params(q), prompt, 6, cfg)
        assert np.array_equal(np.asarray(out_q), np.asarray(out_d))

    def test_prefill_primes_cache_with_quant_params(self):
        cfg = _cfg(rope=True)
        p = quantize_params_int8(init_params(cfg, seed=4))
        prompt = jnp.zeros((1, 5), jnp.int32)
        logits, cache = prefill(p, prompt, cfg)
        assert logits.shape == (1, cfg.vocab)
        assert np.all(np.isfinite(np.asarray(logits, np.float32)))
        assert cache[0]["k"].shape[1] == cfg.max_len


class TestKvCacheQuant:
    """Int8 KV cache (cfg.kv_quant): approximate by design (~0.4%
    per-vector rounding), so the oracle is tolerance-based against the
    float-cache decode of the SAME params — not exactness."""

    def test_cache_layout_and_bytes(self):
        cfg = _cfg(kv_quant="int8")
        cache = init_kv_cache(cfg, 2)
        lay = cache[0]
        assert lay["k"].dtype == jnp.int8 and lay["v"].dtype == jnp.int8
        assert lay["ks"].shape == lay["k"].shape[:-1] + (1,)
        qbytes = sum(x.nbytes for x in lay.values())
        fbytes = sum(x.nbytes
                     for x in init_kv_cache(_cfg(), 2)[0].values())
        # ~4x smaller than f32 + the per-vector scale overhead.
        assert qbytes < 0.3 * fbytes + 8 * lay["ks"].size

    def test_rejects_unknown_mode(self):
        with pytest.raises(ValueError, match="kv_quant"):
            init_kv_cache(_cfg(kv_quant="fp4"), 1)

    @pytest.mark.parametrize("kw", [
        {},
        {"rope": True, "n_kv_heads": 1, "window": 16},
        {"dtype": "bfloat16"},
    ])
    def test_decode_close_to_float_cache(self, kw):
        cfg_f = _cfg(**kw)
        cfg_q = _cfg(kv_quant="int8", **kw)
        p = init_params(cfg_f, seed=6)
        b = 2
        tok0 = jnp.asarray([3, 11], jnp.int32)
        tok1 = jnp.asarray([9, 2], jnp.int32)
        cf = init_kv_cache(cfg_f, b, dtype=jnp.dtype(cfg_f.dtype))
        cq = init_kv_cache(cfg_q, b)
        lf, cf = tr.decode_step(p, cf, tok0, 0, cfg_f)
        lq, cq = tr.decode_step(p, cq, tok0, 0, cfg_q)
        # Step 2 attends cached (quantized) K/V from step 1.
        lf, _ = tr.decode_step(p, cf, tok1, 1, cfg_f)
        lq, _ = tr.decode_step(p, cq, tok1, 1, cfg_q)
        lff = np.asarray(lf, np.float32)
        lqf = np.asarray(lq, np.float32)
        scale = np.abs(lff).max()
        assert np.max(np.abs(lqf - lff)) <= 0.05 * scale

    def test_generate_with_full_int8_stack(self):
        # Weights AND cache int8 — the bench's decodeint8 configuration.
        cfg = _cfg(kv_quant="int8", dtype="bfloat16")
        q = quantize_params_int8(init_params(cfg, seed=7))
        prompt = jnp.asarray(
            np.random.default_rng(1).integers(0, cfg.vocab, (2, 8)),
            jnp.int32)
        out = generate(q, prompt, 6, cfg)
        assert out.shape == (2, 6)
        assert int(jnp.min(out)) >= 0 and int(jnp.max(out)) < cfg.vocab

    def test_prefill_primes_quantized_ring(self):
        # The prompt pass itself never sees quantized K/V (flash kernel on
        # float) — what matters is the FIRST DECODE STEP attending the
        # int8-primed ring matching the float-primed one.
        cfg_q = _cfg(kv_quant="int8", rope=True, window=8, max_len=32)
        cfg_f = _cfg(rope=True, window=8, max_len=32)
        p = init_params(cfg_q, seed=8)
        prompt = jnp.asarray(
            np.random.default_rng(2).integers(0, cfg_q.vocab, (1, 12)),
            jnp.int32)
        _, cache_q = prefill(p, prompt, cfg_q)
        _, cache_f = prefill(p, prompt, cfg_f)
        assert cache_q[0]["k"].dtype == jnp.int8
        assert cache_q[0]["k"].shape[1] == 8  # ring length = window
        tok = jnp.asarray([5], jnp.int32)
        lq, _ = tr.decode_step(p, cache_q, tok, 12, cfg_q)
        lf, _ = tr.decode_step(p, cache_f, tok, 12, cfg_f)
        lff = np.asarray(lf, np.float32)
        lqf = np.asarray(lq, np.float32)
        assert np.max(np.abs(lqf - lff)) <= 0.05 * np.abs(lff).max()


class TestGuards:
    def test_loss_fn_rejects_quantized_params(self):
        cfg = _cfg()
        q = quantize_params_int8(init_params(cfg, seed=0))
        tok = jnp.zeros((1, 8), jnp.int32)
        with pytest.raises(ValueError, match="inference-only"):
            loss_fn(q, tok, tok, cfg)

    def test_shard_params_rejects_quantized_params(self):
        cfg = _cfg()
        q = quantize_params_int8(init_params(cfg, seed=0))
        with pytest.raises(ValueError, match="TP-placed"):
            tr.shard_params(q, cfg)

    def test_decode_rejects_cache_config_mismatch(self):
        # An int8 cache attended by a kv_quant-less cfg would astype-
        # truncate K/V into the int8 buffers and return finite garbage;
        # both mismatch directions must error instead.
        cfg_q = _cfg(kv_quant="int8")
        cfg_f = _cfg()
        p = init_params(cfg_f, seed=0)
        tok = jnp.zeros((1,), jnp.int32)
        with pytest.raises(ValueError, match="int8-quantized"):
            tr.decode_step(p, init_kv_cache(cfg_q, 1), tok, 0, cfg_f)
        with pytest.raises(ValueError, match="int8-quantized"):
            tr.decode_step(p, init_kv_cache(cfg_f, 1), tok, 0, cfg_q)


class TestServeStackIntegration:
    def test_quantized_params_checkpoint_roundtrip(self, tmp_path):
        # The deploy story: train float masters -> quantize once ->
        # checkpoint the int8 artifact -> restore -> serve. The int8
        # pytree ({"q8" int8, "s8" f32} leaves) must survive the orbax
        # round-trip bit-exactly and decode identically.
        from marlin_tpu.utils.checkpoint import load_pytree, save_pytree

        cfg = _cfg(kv_quant="int8")
        q = quantize_params_int8(init_params(cfg, seed=9))
        path = str(tmp_path / "int8_ckpt")
        save_pytree(q, path)
        q2 = load_pytree(path)
        for a, b in zip(jax.tree.leaves(q), jax.tree.leaves(q2)):
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        out1 = generate(q, prompt, 4, cfg)
        out2 = generate(q2, prompt, 4, cfg)
        assert np.array_equal(np.asarray(out1), np.asarray(out2))


class TestStreamingWin:
    def test_int8_decode_streams_a_quarter_of_the_bytes(self):
        cfg = _cfg(vocab=256, d_model=64, d_ff=256, n_layers=2, max_len=64)
        p = init_params(cfg, seed=5)
        q = quantize_params_int8(p)
        b = 2
        tok = jnp.zeros((b,), jnp.int32)
        fn = jax.jit(tr.decode_step, static_argnames="cfg")
        rep_f = cm.compiled_cost(fn, p, init_kv_cache(cfg, b), tok, 1,
                                 cfg=cfg)
        rep_q = cm.compiled_cost(fn, q, init_kv_cache(cfg, b), tok, 1,
                                 cfg=cfg)
        params_f32 = cm.transformer_param_count(cfg) * 4
        # Argument bytes: weights now int8 + small scales — the streamed
        # width the decode roofline divides by. This is the structural,
        # platform-independent win.
        assert rep_q.arg_bytes < rep_f.arg_bytes - 0.6 * params_f32
        # CPU XLA can't fuse the convert/scale into its dot, so it
        # materializes ONE dequantized copy in the temp arena (the TPU
        # fuses the convert into the operand load instead — the bench's
        # tokens/s confirms). Bound it at one copy: a path change that
        # dequantized a weight twice per step doubles this delta and fails.
        assert rep_q.temp_bytes - rep_f.temp_bytes <= 1.25 * params_f32
