"""Ring / streaming parallelism tests: ring GEMM and ring attention vs dense
oracles on the 8-device mesh."""

import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu.parallel.ring import ring_matmul, ring_self_attention


class TestRingMatmul:
    def test_matches_oracle(self, rng):
        a = rng.standard_normal((24, 40))
        b = rng.standard_normal((40, 12))
        out = ring_matmul(a, b)
        np.testing.assert_allclose(np.asarray(out), a @ b, rtol=1e-10)

    def test_uneven_shapes_padded(self, rng):
        a = rng.standard_normal((13, 21))
        b = rng.standard_normal((21, 7))
        out = ring_matmul(a, b)
        assert out.shape == (13, 7)
        np.testing.assert_allclose(np.asarray(out), a @ b, rtol=1e-10)

    def test_mismatch_raises(self, rng):
        with pytest.raises(ValueError):
            ring_matmul(rng.standard_normal((4, 5)), rng.standard_normal((6, 3)))


def _attention_oracle(q, k, v, causal=False, scale=None):
    scale = scale or 1.0 / np.sqrt(q.shape[1])
    logits = scale * (q @ k.T)
    if causal:
        mask = np.tril(np.ones((q.shape[0], k.shape[0]), bool))
        logits = np.where(mask, logits, -1e30)
    w = np.exp(logits - logits.max(axis=1, keepdims=True))
    w /= w.sum(axis=1, keepdims=True)
    return w @ v


class TestRingAttention:
    def test_full_attention(self, rng):
        sq, skv, d = 32, 64, 16
        q = rng.standard_normal((sq, d))
        k = rng.standard_normal((skv, d))
        v = rng.standard_normal((skv, d))
        out = ring_self_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), _attention_oracle(q, k, v), rtol=1e-8, atol=1e-10
        )

    def test_causal(self, rng):
        s, d = 64, 8
        q = rng.standard_normal((s, d))
        k = rng.standard_normal((s, d))
        v = rng.standard_normal((s, d))
        out = ring_self_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out),
            _attention_oracle(q, k, v, causal=True),
            rtol=1e-8,
            atol=1e-10,
        )

    def test_kv_divisibility_contract(self, rng):
        with pytest.raises(ValueError):
            ring_self_attention(
                rng.standard_normal((8, 4)),
                rng.standard_normal((9, 4)),
                rng.standard_normal((9, 4)),
            )
