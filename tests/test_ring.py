"""Ring / streaming parallelism tests: ring GEMM and ring attention vs dense
oracles on the 8-device mesh."""

import jax.numpy as jnp
import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu.parallel.ring import ring_matmul, ring_self_attention


class TestRingMatmul:
    def test_matches_oracle(self, rng):
        a = rng.standard_normal((24, 40))
        b = rng.standard_normal((40, 12))
        out = ring_matmul(a, b)
        np.testing.assert_allclose(np.asarray(out), a @ b, rtol=1e-10)

    def test_uneven_shapes_padded(self, rng):
        a = rng.standard_normal((13, 21))
        b = rng.standard_normal((21, 7))
        out = ring_matmul(a, b)
        assert out.shape == (13, 7)
        np.testing.assert_allclose(np.asarray(out), a @ b, rtol=1e-10)

    def test_mismatch_raises(self, rng):
        with pytest.raises(ValueError):
            ring_matmul(rng.standard_normal((4, 5)), rng.standard_normal((6, 3)))


def _attention_oracle(q, k, v, causal=False, scale=None):
    scale = scale or 1.0 / np.sqrt(q.shape[1])
    logits = scale * (q @ k.T)
    if causal:
        mask = np.tril(np.ones((q.shape[0], k.shape[0]), bool))
        logits = np.where(mask, logits, -1e30)
    w = np.exp(logits - logits.max(axis=1, keepdims=True))
    w /= w.sum(axis=1, keepdims=True)
    return w @ v


class TestRingAttention:
    def test_full_attention(self, rng):
        sq, skv, d = 32, 64, 16
        q = rng.standard_normal((sq, d))
        k = rng.standard_normal((skv, d))
        v = rng.standard_normal((skv, d))
        out = ring_self_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), _attention_oracle(q, k, v), rtol=1e-8, atol=1e-10
        )

    def test_causal(self, rng):
        s, d = 64, 8
        q = rng.standard_normal((s, d))
        k = rng.standard_normal((s, d))
        v = rng.standard_normal((s, d))
        out = ring_self_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out),
            _attention_oracle(q, k, v, causal=True),
            rtol=1e-8,
            atol=1e-10,
        )

    def test_kv_divisibility_contract(self, rng):
        with pytest.raises(ValueError):
            ring_self_attention(
                rng.standard_normal((8, 4)),
                rng.standard_normal((9, 4)),
                rng.standard_normal((9, 4)),
            )


class TestAccumulatorPrecision:
    def test_bf16_inputs_accumulate_in_f32(self, rng):
        # bf16 carries ~3 decimal digits: accumulating the online-softmax
        # state in input dtype across 8 hops drifts ~1e-2; f32 accumulators
        # keep the result near the f64 oracle at bf16-rounding tolerance.
        import jax.numpy as jnp

        s, d = 256, 64
        q = rng.standard_normal((s, d)).astype(np.float32)
        k = rng.standard_normal((s, d)).astype(np.float32)
        v = rng.standard_normal((s, d)).astype(np.float32)
        qb, kb, vb = (jnp.asarray(x, jnp.bfloat16) for x in (q, k, v))
        got = np.asarray(
            ring_self_attention(qb, kb, vb), np.float64
        )
        # Oracle on the bf16-rounded operands (isolates accumulation error).
        qf, kf, vf = (np.asarray(x, np.float64) for x in (qb, kb, vb))
        logits = qf @ kf.T / np.sqrt(d)
        p = np.exp(logits - logits.max(1, keepdims=True))
        oracle = (p / p.sum(1, keepdims=True)) @ vf
        err = np.max(np.abs(got - oracle)) / np.max(np.abs(oracle))
        assert err < 8e-3, err


class TestWindowedRing:
    def test_hop_bounded_ring_matches_banded_oracle(self, rng, mesh):
        n_dev = len(mesh.devices.flat)
        s_len, d, w = 8 * n_dev, 16, 10
        q = jnp.asarray(rng.standard_normal((s_len, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((s_len, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((s_len, d)), jnp.float32)
        got = np.asarray(ring_self_attention(q, k, v, causal=True, window=w))
        qf, kf, vf = (np.asarray(x, np.float64) for x in (q, k, v))
        logits = (qf @ kf.T) / np.sqrt(d)
        kp = np.arange(s_len)[None, :]
        qp = np.arange(s_len)[:, None]
        logits = np.where((kp <= qp) & (kp > qp - w), logits, -1e30)
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        np.testing.assert_allclose(got, p @ vf, rtol=1e-5, atol=1e-5)

    def test_windowed_ring_multihead_and_dispatch(self, rng, mesh):
        from marlin_tpu.parallel.ulysses import sequence_parallel_attention

        n_dev = len(mesh.devices.flat)
        s_len, h, d, w = 8 * n_dev, n_dev, 16, 12
        q, k, v = (jnp.asarray(rng.standard_normal((s_len, h, d)),
                               jnp.float32) for _ in range(3))
        outs = {}
        for strat in ("ring", "all_to_all"):
            outs[strat] = np.asarray(sequence_parallel_attention(
                q, k, v, causal=True, strategy=strat, window=w))
        np.testing.assert_allclose(outs["ring"], outs["all_to_all"],
                                   rtol=1e-4, atol=1e-4)

    def test_window_requires_causal_and_self_lengths(self, rng, mesh):
        n_dev = len(mesh.devices.flat)
        q = jnp.zeros((8 * n_dev, 8), jnp.float32)
        with pytest.raises(ValueError, match="causal"):
            ring_self_attention(q, q, q, window=4)
        k = jnp.zeros((16 * n_dev, 8), jnp.float32)
        with pytest.raises(ValueError, match="self-attention"):
            ring_self_attention(q, k, k, causal=True, window=4)

    def test_negative_window_rejected(self, mesh):
        n_dev = len(mesh.devices.flat)
        q = jnp.zeros((8 * n_dev, 8), jnp.float32)
        with pytest.raises(ValueError, match=">= 0"):
            ring_self_attention(q, q, q, causal=True, window=-4)

    def test_window_one_single_hop(self, rng, mesh):
        # window=1 attends only the diagonal: one hop, output == v.
        import numpy as np

        n_dev = len(mesh.devices.flat)
        s_len = 4 * n_dev
        q = jnp.asarray(rng.standard_normal((s_len, 8)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((s_len, 8)), jnp.float32)
        got = np.asarray(ring_self_attention(q, q, v, causal=True, window=1))
        np.testing.assert_allclose(got, np.asarray(v), rtol=1e-6, atol=1e-6)
