"""Chaos suite for the fault-tolerant serving layer (PR 7,
docs/robustness.md): serving/faults.py + the frontend supervisor.

The acceptance claims, each pinned mechanically:

* BIT-EXACT RECOVERY — with a fault injected at EVERY site
  (decode_round / prefill_chunk one-shot + chunked / prefix_copy /
  admission_pop / stream_fanout / runlog_emit), every in-flight and
  queued request's recovered output is bit-identical to an
  uninterrupted solo run, greedy AND sampled (per-request PRNG streams
  make output a pure function of ``(prompt, steps, seed, request_id)``)
  — and streamed SSE chunk sequences concatenate byte-identically
  across the restart (the cursor deduplicates delivered tokens).
* EXACT ACCOUNTING — none lost, none duplicated: completed + timed out
  + quarantined == submitted, handles all resolved, counters to the
  unit.
* WARM RESTART — zero compile events after the crash round (the
  successor reuses the module-level jit caches).
* POISON QUARANTINE — a request implicated in 2 consecutive crashes is
  failed with a typed ``PoisonedRequest`` (HTTP 500, structured body)
  instead of requeued; the engine keeps serving everyone else.
* FAIL CLOSED — past ``max_restarts`` in the window, waiters get
  ``EngineFailed``, new submits are refused, ``/readyz`` goes false.
* DEADLINES SURVIVE — a requeued request keeps its ORIGINAL
  ``deadline_time``; one that expired during the crash window resolves
  as a normal timeout, not a recovery retry.
* CLIENT RETRY — deterministic backoff schedule, Retry-After honored,
  budget enforced, idempotent-only by default.

The subprocess smoke at the bottom is the CI form: a real server armed
via ``MARLIN_FAULT_PLAN``, crashed mid-stream, recovered byte-exactly,
``/metrics`` showing exactly one restart, and the sealed runlog passing
tools/runlog_report.py's crash-cycle detector.
"""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from marlin_tpu.models import TransformerConfig, init_params
from marlin_tpu.obs.metrics import MetricsRegistry
from marlin_tpu.serving import (EngineFailed, EngineFrontend,
                                PoisonedRequest, PrefixCache,
                                ServingEngine, faults, serve)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod  # dataclass annotations resolve via here
    spec.loader.exec_module(mod)
    return mod


def _cfg(**kw):
    base = dict(vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                max_len=64)
    base.update(kw)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return init_params(cfg, seed=0), cfg


@pytest.fixture(autouse=True)
def _clean_plan():
    """No chaos plan leaks across tests — injection is opt-in per
    test."""
    yield
    faults.reset()


def _prompts(cfg, n, length=16, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, cfg.vocab, length).astype(np.int32)
            for _ in range(n)]


def _golden(params, cfg, prompts, steps, **eng_kw):
    """Uninterrupted solo run of the same workload (ids 0..n-1 in
    submission order) — the bit-exactness reference."""
    eng_kw.setdefault("metrics_registry", MetricsRegistry())
    eng = ServingEngine(params, cfg, **eng_kw)
    for p in prompts:
        eng.submit(p, steps)
    return {r.request_id: list(map(int, r.tokens)) for r in eng.run()}


def _run_chaos(params, cfg, specs, n=6, steps=6, temperature=0.0,
               stream_mod=2, **eng_kw):
    """Install ``specs``, run ``n`` requests (every ``stream_mod``-th
    one streaming) through a supervised frontend; returns
    ``(frontend, registry, streamed-by-id, results-by-id)``. The fault
    plan is active only during this run."""
    plan = faults.install(faults.FaultPlan())
    for s in specs:
        plan.add(**s)
    reg = MetricsRegistry()
    eng_kw.setdefault("batch", 2)
    eng_kw.setdefault("round_steps", 2)
    eng = ServingEngine(params, cfg, temperature=temperature,
                        metrics_registry=reg, **eng_kw)
    fe = EngineFrontend(eng).start()
    handles = [fe.submit(p, steps, stream=(i % stream_mod == 0))
               for i, p in enumerate(_prompts(cfg, n))]
    streamed = {}
    for h in handles:
        if h.stream:
            toks = []
            for chunk in h.chunks():
                toks.extend(int(t) for t in chunk)
            streamed[h.request_id] = toks
    results = {h.request_id: h.result(60.0) for h in handles}
    faults.reset()
    return fe, reg, streamed, results


def _assert_exact_accounting(fe, reg, n, quarantined=0, timeout=0):
    st = fe.engine.stats
    assert st.n_completed + st.n_timeout + st.n_quarantined == n
    assert st.n_quarantined == quarantined
    assert st.n_timeout == timeout
    assert reg.counter("serving_submitted_total").value == n
    assert reg.counter("serving_completed_total").value == st.n_completed
    assert len(fe.engine.requests) == 0  # ownership fully transferred
    assert len(fe._handles) == 0


# -- the fault plan itself (unit) -------------------------------------


class TestFaultPlan:
    def test_spec_validation(self):
        with pytest.raises(ValueError):
            faults.FaultSpec(site="nope")
        with pytest.raises(ValueError):
            faults.FaultSpec(site="decode_round", action="explode")
        with pytest.raises(ValueError):
            faults.FaultSpec(site="decode_round", max_fires=0)
        with pytest.raises(ValueError):
            # A zero modulus would ZeroDivisionError on every check —
            # a config typo must fail at install, not as a crash loop.
            faults.FaultSpec(site="decode_round", round_every=0)

    def test_deterministic_matching_and_max_fires(self):
        plan = faults.FaultPlan()
        plan.add(site="decode_round", round=3, max_fires=1)
        plan.check("decode_round", round_idx=2)  # no match
        plan.check("prefill_chunk", round_idx=3)  # wrong site
        with pytest.raises(faults.FaultInjected):
            plan.check("decode_round", round_idx=3)
        plan.check("decode_round", round_idx=3)  # consumed: max_fires=1
        assert plan.total_fires() == 1

    def test_round_every_and_request_predicates(self):
        plan = faults.FaultPlan()
        plan.add(site="prefill_chunk", round_every=2, request_id=5,
                 max_fires=10)
        plan.check("prefill_chunk", round_idx=1, request_id=5)  # odd
        plan.check("prefill_chunk", round_idx=2, request_id=4)  # wrong id
        with pytest.raises(faults.FaultInjected):
            plan.check("prefill_chunk", round_idx=2, request_id=5)

    def test_delay_and_corrupt_actions(self):
        plan = faults.FaultPlan()
        plan.add(site="decode_round", action="delay", round=0,
                 delay_s=0.01)
        t0 = time.perf_counter()
        plan.check("decode_round", round_idx=0)  # sleeps, no raise
        assert time.perf_counter() - t0 >= 0.009
        plan.add(site="decode_round", action="corrupt", round=1)
        arr = np.arange(4, dtype=np.int32) + 1
        out = plan.corrupt("decode_round", arr, round_idx=1)
        assert out[0] == -1 and arr[0] == 1  # scribbled COPY
        same = plan.corrupt("decode_round", arr, round_idx=1)
        assert same is arr  # spec consumed

    def test_json_roundtrip_and_env_install(self):
        plan = faults.FaultPlan()
        plan.add(site="decode_round", round=4)
        plan2 = faults.FaultPlan.from_json(plan.to_json())
        assert plan2.specs[0].site == "decode_round"
        assert plan2.specs[0].round == 4
        assert plan2.specs[0].fires == 0  # firing state not inherited
        installed = faults.install_from_env(
            {faults.ENV_VAR: plan.to_json()})
        assert faults.active() is installed
        # The bare-list form is accepted too.
        bare = faults.FaultPlan.from_json(
            '[{"site": "decode_round", "round": 4}]')
        assert bare.specs[0].round == 4
        assert faults.install_from_env({}) is None  # unset: no-op

    def test_no_plan_fast_path(self):
        faults.reset()
        faults.check("decode_round", round_idx=0)
        arr = np.ones(2)
        assert faults.corrupt("decode_round", arr) is arr


# -- supervised restart: bit-exact recovery ---------------------------


class TestBitExactRecovery:
    @pytest.mark.parametrize("temperature", [0.0, 0.7],
                             ids=["greedy", "sampled"])
    def test_decode_round_crash_recovers_bitexact(self, model,
                                                  temperature):
        """The tentpole pin: crash mid-serving at a decode round; every
        request (streamed and blocking) completes bit-identical to an
        uninterrupted run — greedy and sampled alike — with exactly one
        restart and zero post-restart compiles."""
        params, cfg = model
        prompts = _prompts(cfg, 6)
        gold = _golden(params, cfg, prompts, 6, batch=2, round_steps=2,
                       temperature=temperature)
        fe, reg, streamed, results = _run_chaos(
            params, cfg, [dict(site="decode_round", round=2)],
            n=6, steps=6, temperature=temperature)
        assert fe.restarts == 1
        assert all(r.status == "done" for r in results.values())
        for rid, r in results.items():
            assert list(map(int, r.tokens)) == gold[rid], rid
        # Streamed chunk sequences concatenate byte-identically across
        # the restart: the cursor deduplicated pre-crash deliveries.
        for rid, toks in streamed.items():
            assert toks == gold[rid], rid
        _assert_exact_accounting(fe, reg, 6)
        assert reg.counter("serving_engine_restarts_total").value == 1
        assert reg.counter(
            "serving_requests_recovered_total").value >= 1
        # Fired faults are visible process-wide (faults.py bumps the
        # global registry — chaos runs distinguish injected crashes
        # from organic ones even when engines pin their own registry).
        from marlin_tpu.obs import metrics as obs_metrics
        assert obs_metrics.registry.counter(
            "serving_faults_injected_total",
            site="decode_round").value >= 1
        # Warm restart: no compile events after the crash round.
        late = [e for e in fe.engine.runlog.events("compile")
                if e["round"] > 2]
        assert late == [], late
        # The crash narrative is in the runlog.
        kinds = [e["kind"] for e in fe.engine.runlog.events()]
        assert "engine_crash" in kinds and "recover" in kinds
        # Requests IN FLIGHT at the crash carry the recovery
        # sub-attribution (time sunk into the dead attempt), and the
        # contiguous phase sum still equals total exactly.
        rec = [r for r in results.values() if r.crash_count]
        assert rec  # the crash did interrupt someone mid-flight
        for r in rec:
            ph = r.phases()
            assert ph["recovery"] > 0
            assert ph["queue_wait"] + ph["admit"] + ph["decode"] \
                == pytest.approx(ph["total"], rel=1e-9, abs=1e-12)
        assert fe.drain(30.0)

    @pytest.mark.parametrize("site,specs,eng_kw", [
        # admission_pop only runs while a slot is FREE: with 6 equal
        # requests on batch=2, the first retirement frees rows at the
        # round-2 boundary, so round 3's pop is the first mid-flight one.
        ("admission_pop",
         [dict(site="admission_pop", round=3)], {}),
        ("runlog_emit",
         [dict(site="runlog_emit", round=2)], {}),
        ("stream_fanout",
         [dict(site="stream_fanout", round=2)], {}),
        ("prefill_oneshot",
         [dict(site="prefill_chunk", request_id=3)], {}),
        ("prefill_chunked",
         [dict(site="prefill_chunk", request_id=3)],
         {"prefill_chunk": 32}),
    ])
    def test_every_site_recovers_bitexact(self, model, site, specs,
                                          eng_kw):
        params, cfg = model
        prompts = _prompts(cfg, 6)
        gold = _golden(params, cfg, prompts, 6, batch=2, round_steps=2,
                       **eng_kw)
        fe, reg, streamed, results = _run_chaos(
            params, cfg, specs, n=6, steps=6, **eng_kw)
        assert fe.restarts == 1, site
        assert all(r.status == "done" for r in results.values())
        for rid, r in results.items():
            assert list(map(int, r.tokens)) == gold[rid], (site, rid)
        for rid, toks in streamed.items():
            assert toks == gold[rid], (site, rid)
        _assert_exact_accounting(fe, reg, 6)
        assert fe.drain(30.0)

    def test_prefix_copy_crash_recovers_bitexact(self, model):
        """Crash inside the prefix-cache donor copy: the successor gets
        a FRESH pool (torn refcounts discarded) and replays bit-exactly
        — cache state is a pure perf layer, never a correctness one."""
        params, cfg = model
        rng = np.random.default_rng(5)
        shared = rng.integers(0, cfg.vocab, 32).astype(np.int32)
        prompts = [np.concatenate([shared, rng.integers(
            0, cfg.vocab, 8).astype(np.int32)]) for _ in range(5)]
        kw = dict(batch=2, round_steps=2, prefill_chunk=16)
        eng_gold = ServingEngine(params, cfg,
                                 metrics_registry=MetricsRegistry(),
                                 **kw)
        for p in prompts:
            eng_gold.submit(p, 4)
        gold = {r.request_id: list(map(int, r.tokens))
                for r in eng_gold.run()}
        plan = faults.install(faults.FaultPlan())
        # Request 2 shares request 0's stored prefix -> its admission
        # starts with a pool copy, which crashes.
        plan.add(site="prefix_copy", request_id=2)
        reg = MetricsRegistry()
        eng = ServingEngine(params, cfg, metrics_registry=reg,
                            prefix_cache=PrefixCache(cfg, pool_rows=4),
                            **kw)
        fe = EngineFrontend(eng).start()
        handles = [fe.submit(p, 4) for p in prompts]
        results = {h.request_id: h.result(60.0) for h in handles}
        faults.reset()
        assert plan.total_fires() == 1  # the copy path really ran
        assert fe.restarts == 1
        for rid, r in results.items():
            assert list(map(int, r.tokens)) == gold[rid], rid
        _assert_exact_accounting(fe, reg, 5)
        assert fe.drain(30.0)

    def test_paged_alias_crash_discards_torn_refcounts(self, model):
        """PAGED engine, crash landing MID prefix-hit admission: the
        fault fires at the same ``prefix_copy`` site, after the hit's
        pages were refcount-pinned but before the row armed — exactly
        the torn-refcount state ``spawn_successor`` exists to discard.
        The successor gets a FRESH PagePool + index, replays
        bit-exactly, and ends with a pool whose only references are its
        own stored prefixes (no leaked pins from the dead
        incarnation)."""
        params, cfg = model
        rng = np.random.default_rng(5)
        shared = rng.integers(0, cfg.vocab, 32).astype(np.int32)
        prompts = [np.concatenate([shared, rng.integers(
            0, cfg.vocab, 8).astype(np.int32)]) for _ in range(5)]
        kw = dict(batch=2, round_steps=2, kv_pages=12)
        eng_gold = ServingEngine(params, cfg,
                                 metrics_registry=MetricsRegistry(),
                                 **kw)
        for p in prompts:
            eng_gold.submit(p, 4)
        gold = {r.request_id: list(map(int, r.tokens))
                for r in eng_gold.run()}
        plan = faults.install(faults.FaultPlan())
        # Request 2 shares request 0's stored prefix -> its admission
        # takes the zero-copy alias path, which crashes mid-pin.
        plan.add(site="prefix_copy", request_id=2)
        reg = MetricsRegistry()
        eng = ServingEngine(params, cfg, metrics_registry=reg, **kw)
        crashed_pool = eng.page_pool
        fe = EngineFrontend(eng).start()
        handles = [fe.submit(p, 4) for p in prompts]
        results = {h.request_id: h.result(60.0) for h in handles}
        faults.reset()
        assert plan.total_fires() == 1  # the alias path really ran
        assert fe.restarts == 1
        for rid, r in results.items():
            assert list(map(int, r.tokens)) == gold[rid], rid
        _assert_exact_accounting(fe, reg, 5)
        # No double-count across the replay: hit/miss accounting lands
        # AFTER the aliasing fault site, so the crashed attempt (which
        # fired mid-pin, before the record) contributes nothing — every
        # recorded lookup corresponds to an admission that completed.
        st = fe.engine.stats
        assert st.n_prefix_hits + st.n_prefix_misses == st.n_admitted
        # The successor rebuilt storage from scratch; the crashed
        # pool's torn pins were discarded wholesale with it.
        pool = fe.engine.page_pool
        assert pool is not crashed_pool
        stored = sum(e.length // 16
                     for e in fe.engine.prefix_index._entries.values())
        assert pool.n_used == stored  # rows all retired: no leaked refs
        assert fe.drain(30.0)

    def test_corrupted_fetch_is_detected_and_recovered(self, model):
        """A corrupted device fetch is not served: the engine's sanity
        bounds raise EngineStateCorrupt, the supervisor rebuilds, and
        the replay is bit-exact."""
        params, cfg = model
        prompts = _prompts(cfg, 4)
        gold = _golden(params, cfg, prompts, 6, batch=2, round_steps=2)
        fe, reg, _, results = _run_chaos(
            params, cfg,
            [dict(site="decode_round", action="corrupt", round=2)],
            n=4, steps=6, stream_mod=10)
        assert fe.restarts == 1
        for rid, r in results.items():
            assert list(map(int, r.tokens)) == gold[rid], rid
        crash = fe.engine.runlog.events("engine_crash")[0]
        assert crash["error_type"] == "EngineStateCorrupt"
        _assert_exact_accounting(fe, reg, 4)
        assert fe.drain(30.0)

    def test_kv_restore_crash_rebuilds_tier_and_readopts(self, model,
                                                         tmp_path):
        """Chaos plan firing MID-RESTORE (the host-tier scatter,
        ISSUE 16): the successor rebuilds a FRESH host tier — in-memory
        payloads discarded wholesale, the coherent crash story — while
        the ``spill_dir``'s durable payload survives, so the REPLAYED
        admission re-adopts the dead incarnation's spill from disk and
        restores it bit-exactly (the fault is one-shot; the second
        restore lands)."""
        params, cfg = model
        rng = np.random.default_rng(5)
        shared = rng.integers(0, cfg.vocab, 32).astype(np.int32)

        def prompt(i):
            if i in (0, 3):  # the shared-prefix pair
                return np.concatenate([shared, rng.integers(
                    0, cfg.vocab, 8).astype(np.int32)])
            return np.random.default_rng(50 + i).integers(
                0, cfg.vocab, 40).astype(np.int32)

        prompts = [prompt(i) for i in range(4)]
        kw = dict(batch=2, round_steps=2, prefill_chunk=16)
        gold = _golden(params, cfg, prompts, 4, kv_pages=7, **kw)
        plan = faults.install(faults.FaultPlan())
        plan.add(site="kv_restore")  # one-shot: first restore crashes
        reg = MetricsRegistry()
        eng = ServingEngine(params, cfg, metrics_registry=reg,
                            kv_pages=7, host_kv_bytes=1 << 22,
                            host_kv_dir=str(tmp_path),
                            restore_min_tokens=16, **kw)
        crashed_pool, crashed_tier = eng.page_pool, eng.host_tier
        fe = EngineFrontend(eng).start()
        # Phased so the spill -> restore sequence is deterministic:
        # req 0 stores the shared prefix; the churn pair's reservations
        # force its eviction (spill, kv_pages=7 leaves no slack); req 3
        # hits the spilled prefix and its admission restores — where
        # the fault fires.
        results = {}
        for batch in ([0], [1, 2], [3]):
            handles = [fe.submit(prompts[i], 4) for i in batch]
            for h in handles:
                results[h.request_id] = h.result(60.0)
        faults.reset()
        assert plan.total_fires() == 1  # the restore path really ran
        assert fe.restarts == 1
        for rid, r in results.items():
            assert list(map(int, r.tokens)) == gold[rid], rid
        _assert_exact_accounting(fe, reg, 4)
        from marlin_tpu.obs import metrics as obs_metrics
        assert obs_metrics.registry.counter(
            "serving_faults_injected_total",
            site="kv_restore").value >= 1
        # The successor rebuilt BOTH storage layers from scratch.
        succ = fe.engine
        assert succ.page_pool is not crashed_pool
        assert succ.host_tier is not crashed_tier
        assert succ.host_tier.summary()["spill_dir"] == str(tmp_path)
        # The torn restore left nothing behind: every device reference
        # is a stored prefix's own pin (rows all retired).
        stored = sum(len(e.pages)
                     for e in succ.prefix_index._entries.values())
        assert succ.page_pool.n_used == stored
        # The replay went through the DURABLE half: the predecessor's
        # spill file was adopted by the fresh tier and restored (the
        # fresh incarnation never spilled anything itself first).
        assert succ.prefix_index.adoptions >= 1
        assert succ.host_tier.summary()["restores"] >= 1
        assert any(f.endswith(".npz") for f in os.listdir(tmp_path))
        restores = [e for e in fe.engine.runlog.events("restore")]
        assert restores and all(e["bytes"] > 0 for e in restores)
        assert fe.drain(30.0)


# -- poison quarantine + fail closed ----------------------------------


class TestQuarantineAndFailClosed:
    def test_poison_request_quarantined_after_two_crashes(self, model):
        """A request whose OWN admission dispatch kills the engine
        twice is quarantined — typed PoisonedRequest, recorded in the
        ledger — and everyone else completes bit-exactly; the engine
        stays up and ready."""
        params, cfg = model
        prompts = _prompts(cfg, 4)
        gold = _golden(params, cfg, prompts, 6, batch=2, round_steps=2)
        plan = faults.install(faults.FaultPlan())
        plan.add(site="prefill_chunk", request_id=1, max_fires=2)
        reg = MetricsRegistry()
        eng = ServingEngine(params, cfg, batch=2, round_steps=2,
                            metrics_registry=reg)
        fe = EngineFrontend(eng).start()  # poison_after=2 default
        handles = [fe.submit(p, 6) for p in prompts]
        outcomes = {}
        for h in handles:
            try:
                outcomes[h.request_id] = h.result(60.0)
            except PoisonedRequest as e:
                outcomes[h.request_id] = e
        faults.reset()
        poisoned = outcomes[1]
        assert isinstance(poisoned, PoisonedRequest)
        assert poisoned.request_id == 1 and poisoned.crash_count == 2
        for rid in (0, 2, 3):
            assert outcomes[rid].status == "done"
            assert list(map(int, outcomes[rid].tokens)) == gold[rid]
        assert fe.restarts == 2
        assert fe.ready  # quarantine stopped the crash loop
        st = fe.engine.stats
        assert st.n_quarantined == 1
        (qrec,) = st.quarantine_snapshot()
        assert qrec["request_id"] == 1 and qrec["crash_count"] == 2
        assert reg.counter(
            "serving_requests_quarantined_total").value == 1
        _assert_exact_accounting(fe, reg, 4, quarantined=1)
        q_events = fe.engine.runlog.events("quarantine")
        assert [e["request_id"] for e in q_events] == [1]
        # Blame attribution: the admission crash implicated ONLY the
        # poison request — its neighbors carry no crash count.
        for rid in (0, 2, 3):
            assert outcomes[rid].crash_count == 0, rid
        assert fe.drain(30.0)

    def test_unrelated_crashes_far_apart_do_not_poison(self, model):
        """The CONSECUTIVE in 'poison_after consecutive crashes' is
        literal: an implication older than restart_window_s is stale —
        the streak restarts at 1 — so two unrelated batch-wide crashes
        far apart never 500 a long-running request."""
        params, cfg = model
        plan = faults.install(faults.FaultPlan())
        plan.add(site="decode_round", round=2)
        # Stretch wall-clock past the (tiny) window between the two
        # crashes. round_every=1 also fires on rounds 0-1 (before the
        # first crash), so budget 7 fires: the 5 POST-crash delays on
        # rounds 3-7 put 0.4 s > restart_window_s between the crashes.
        plan.add(site="decode_round", action="delay", round_every=1,
                 max_fires=7, delay_s=0.08)
        plan.add(site="decode_round", round=8)
        reg = MetricsRegistry()
        eng = ServingEngine(params, cfg, batch=2, round_steps=2,
                            metrics_registry=reg)
        fe = EngineFrontend(eng, restart_window_s=0.2).start()
        handles = [fe.submit(p, 24) for p in _prompts(cfg, 2)]
        results = [h.result(120.0) for h in handles]
        faults.reset()
        assert fe.restarts == 2
        assert all(r.status == "done" for r in results)
        assert all(r.crash_count <= 1 for r in results)  # streak reset
        assert fe.engine.stats.n_quarantined == 0
        _assert_exact_accounting(fe, reg, 2)
        assert fe.drain(30.0)

    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_recovery_failure_fails_closed_not_silent(self, model):
        """If RECOVERY ITSELF dies (successor can't be built), the
        frontend still fails closed — _fatal set, waiters failed,
        submits refused — never a silent zombie driver."""
        params, cfg = model
        plan = faults.install(faults.FaultPlan())
        plan.add(site="decode_round", round=1)
        eng = ServingEngine(params, cfg, batch=2, round_steps=2,
                            metrics_registry=MetricsRegistry())

        def broken_successor():
            raise RuntimeError("no device memory for a successor")

        eng.spawn_successor = broken_successor
        fe = EngineFrontend(eng).start()
        handles = [fe.submit(p, 6) for p in _prompts(cfg, 2)]
        for h in handles:
            with pytest.raises(EngineFailed, match="recovery failed"):
                h.result(60.0)
        faults.reset()
        assert not fe.ready
        with pytest.raises(EngineFailed):
            fe.submit(_prompts(cfg, 1)[0], 2)

    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_restart_cap_fails_closed(self, model):
        """Past max_restarts in the window: waiters get EngineFailed,
        new submits are refused, ready goes false — fail closed, not
        crash-loop forever. (The driver thread dying LOUDLY with the
        typed verdict is part of the contract — hence the filtered
        unhandled-thread warning.)"""
        params, cfg = model
        plan = faults.install(faults.FaultPlan())
        plan.add(site="decode_round", round_every=1, max_fires=50)
        reg = MetricsRegistry()
        eng = ServingEngine(params, cfg, batch=2, round_steps=2,
                            metrics_registry=reg)
        # poison_after out of reach: this pins the CAP, not quarantine.
        fe = EngineFrontend(eng, max_restarts=2,
                            poison_after=10).start()
        handles = [fe.submit(p, 6) for p in _prompts(cfg, 4)]
        for h in handles:
            with pytest.raises(EngineFailed):
                h.result(60.0)
        faults.reset()
        assert not fe.ready
        deadline = time.perf_counter() + 10.0
        while fe.alive and time.perf_counter() < deadline:
            time.sleep(0.01)  # the driver thread dies loudly
        assert not fe.alive
        with pytest.raises(EngineFailed):
            fe.submit(_prompts(cfg, 1)[0], 2)
        assert reg.counter("serving_engine_restarts_total").value == 2
        kinds = [e["kind"] for e in fe.engine.runlog.events()]
        assert "engine_failed" in kinds
        assert len(fe._handles) == 0  # every waiter was failed


# -- deadlines across recovery (satellite) ----------------------------


class TestDeadlinesAcrossRecovery:
    def test_requeued_keeps_deadline_and_expiry_is_timeout(self, model):
        """A requeued request keeps its ORIGINAL wall-clock deadline;
        one whose deadline passed during the crash window resolves as a
        normal timeout (504 semantics), not a recovery retry."""
        params, cfg = model
        plan = faults.install(faults.FaultPlan())
        plan.add(site="decode_round", round=1)
        reg = MetricsRegistry()
        eng = ServingEngine(params, cfg, batch=1, round_steps=2,
                            metrics_registry=reg)
        fe = EngineFrontend(eng).start()
        prompts = _prompts(cfg, 3)
        h0 = fe.submit(prompts[0], 12)  # occupies the only slot
        h1 = fe.submit(prompts[1], 4, deadline_s=30.0)   # generous
        h2 = fe.submit(prompts[2], 4, deadline_s=0.001)  # hopeless
        # The engine-side Request objects survive the requeue by
        # identity — capture their deadlines now.
        req1 = fe.engine.requests[h1.request_id]
        req2 = fe.engine.requests[h2.request_id]
        d1, d2 = req1.deadline_time, req2.deadline_time
        r0 = h0.result(60.0)
        r1 = h1.result(60.0)
        r2 = h2.result(60.0)
        faults.reset()
        assert fe.restarts == 1
        assert r0.status == "done"
        assert r1.status == "done"
        assert r1 is req1 and r1.deadline_time == d1  # kept, not reset
        assert r1.requeues == 1
        assert r2.status == "timeout"  # expiry, not a recovery retry
        assert r2 is req2 and r2.deadline_time == d2
        assert r2.admit_round == -1  # never admitted post-recovery
        _assert_exact_accounting(fe, reg, 3, timeout=1)
        assert fe.drain(30.0)


# -- HTTP surface: 500 poison body, restart transparency --------------


class TestHTTPFailureSurface:
    def test_poison_maps_to_500_and_server_stays_ready(self, model):
        params, cfg = model
        sc = _load_tool("serving_client")
        plan = faults.install(faults.FaultPlan())
        plan.add(site="prefill_chunk", request_id=1, max_fires=2)
        srv = serve(params, cfg, port=0, batch=2, round_steps=2,
                    max_pending=8, seed=0).start_background()
        try:
            c = sc.ServingClient(port=srv.port)
            prompts = _prompts(cfg, 3, seed=9)
            # serve() shares the PROCESS registry: deltas, not
            # absolutes.
            base = c.metrics()["samples"]
            base_restarts = base.get("serving_engine_restarts_total", 0)
            base_quarantined = base.get(
                "serving_requests_quarantined_total", 0)
            warm = c.generate(prompts[0], 4)  # id 0
            assert warm["code"] == 200
            poisoned = c.generate(prompts[1], 4)  # id 1: crashes twice
            faults.reset()
            assert poisoned["code"] == 500
            assert poisoned["status"] == "poisoned"
            assert poisoned["request_id"] == 1
            assert poisoned["crash_count"] == 2
            # The engine recovered: service ready, next request serves.
            rz = c.readyz()
            assert rz["code"] == 200 and rz["ready"]
            after = c.generate(prompts[2], 4)
            assert after["code"] == 200 and after["status"] == "done"
            # The restart/quarantine counters are scrapeable.
            samples = c.metrics()["samples"]
            assert samples.get("serving_engine_restarts_total", 0) \
                - base_restarts == 2
            assert samples.get(
                "serving_requests_quarantined_total", 0) \
                - base_quarantined == 1
            # /debug/engine narrates the supervisor state.
            code, body, _ = c._get("/debug/engine")
            assert code == 200
            dbg = json.loads(body)
            assert dbg["frontend"]["restarts"] == 2
            assert dbg["frontend"]["failed"] is False
            assert dbg["stats"]["quarantined"] == 1
        finally:
            faults.reset()
            srv.begin_drain(60.0)


# -- SSE disconnect mid-stream (satellite) ----------------------------


class TestStreamAbandon:
    def test_client_disconnect_abandons_stream_request_completes(
            self, model):
        import http.client

        params, cfg = model
        sc = _load_tool("serving_client")
        srv = serve(params, cfg, port=0, batch=2, round_steps=2,
                    max_pending=8, seed=0).start_background()
        try:
            c = sc.ServingClient(port=srv.port)
            # serve() shares the PROCESS registry — measure deltas, not
            # absolutes, so earlier tests' traffic doesn't interfere.
            base = c.metrics()["samples"]
            base_abandoned = base.get(
                "serving_streams_abandoned_total", 0)
            base_completed = base.get("serving_completed_total", 0)
            # Raw streaming request we will abandon after one chunk.
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=30)
            body = json.dumps({"prompt": [1, 2, 3, 4], "steps": 40,
                               "stream": True})
            conn.request("POST", "/v1/generate", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            got = b""
            while b"data: " not in got:  # first chunk arrived
                got += resp.read1(256)
            conn.close()  # hang up mid-stream
            # The server detects the broken pipe on a later write,
            # stops fanout, and the request STILL completes.
            deadline = time.perf_counter() + 30.0
            abandoned = completed = 0
            while time.perf_counter() < deadline:
                samples = c.metrics()["samples"]
                abandoned = samples.get(
                    "serving_streams_abandoned_total", 0) \
                    - base_abandoned
                completed = samples.get("serving_completed_total", 0) \
                    - base_completed
                if abandoned >= 1 and completed >= 1:
                    break
                time.sleep(0.1)
            assert abandoned == 1
            assert completed == 1  # the abandoned request finished
            kinds = [e["kind"] for e in srv.runlog.events()]
            assert "stream_abandoned" in kinds
            # The service is unaffected: a fresh request round-trips.
            r = c.generate([1, 2, 3, 4], 4)
            assert r["code"] == 200 and r["status"] == "done"
        finally:
            srv.begin_drain(60.0)


# -- client retry/backoff (tentpole part 4) ---------------------------


class TestClientRetry:
    def _policy(self, **kw):
        sc = _load_tool("serving_client")
        return sc, sc.RetryPolicy(**kw)

    def test_delay_is_deterministic_and_bounded(self):
        sc, p = self._policy()
        assert p.delay(0, "key-a") == p.delay(0, "key-a")  # replayable
        assert p.delay(0, "key-a") != p.delay(0, "key-b")  # decorrelated
        for attempt in range(8):
            d = p.delay(attempt, "k")
            base = min(p.max_delay_s,
                       p.base_delay_s * p.multiplier ** attempt)
            assert 0.5 * base <= d <= base
        assert p.delay(10, "k") <= p.max_delay_s
        # Retry-After is a floor, not a suggestion.
        assert p.delay(0, "k", retry_after="3") >= 3.0
        assert p.delay(0, "k", retry_after="junk") == p.delay(0, "k")

    def test_retries_shed_codes_until_success(self):
        sc, p = self._policy(max_attempts=4, budget_s=60.0)
        seq = iter([{"code": 429, "retry_after": None, "tokens": []},
                    {"code": 503, "tokens": []},
                    {"code": 200, "tokens": [7], "status": "done"}])
        sleeps = []
        res = sc.call_with_retry(lambda: next(seq), p, "k",
                                 sleep=sleeps.append)
        assert res["code"] == 200 and res["attempts"] == 3
        assert res["retried_codes"] == [429, 503]
        assert len(sleeps) == 2 and all(s > 0 for s in sleeps)

    def test_non_retryable_and_budget(self):
        sc, p = self._policy(max_attempts=5)
        res = sc.call_with_retry(
            lambda: {"code": 400, "tokens": []}, p, "k",
            sleep=lambda s: None)
        assert res["attempts"] == 1  # 400 is not retryable
        sc2, tight = self._policy(max_attempts=5, budget_s=0.01,
                                  base_delay_s=1.0)
        res2 = sc2.call_with_retry(
            lambda: {"code": 429, "tokens": []}, tight, "k",
            sleep=lambda s: None)
        assert res2["attempts"] == 1  # first backoff busts the budget
        assert res2["code"] == 429

    def test_connect_errors_retry_but_partial_streams_do_not(self):
        sc, p = self._policy(max_attempts=3)
        calls = [0]

        def flaky():
            calls[0] += 1
            if calls[0] == 1:
                raise ConnectionResetError("boom")
            return {"code": 200, "tokens": [1], "status": "done"}

        res = sc.call_with_retry(flaky, p, "k", sleep=lambda s: None)
        assert res["code"] == 200 and res["attempts"] == 2
        # A stream that already delivered tokens is NOT idempotent:
        # no silent retry without opt-in.
        partial = {"code": 200, "tokens": [1, 2],
                   "stream_error": "ConnectionResetError: mid-flight"}
        res2 = sc.call_with_retry(lambda: dict(partial), p, "k",
                                  sleep=lambda s: None)
        assert res2["attempts"] == 1
        # ... unless the caller opts in.
        sc3, optin = self._policy(max_attempts=3,
                                  retry_streamed_partial=True)
        seq = iter([dict(partial),
                    {"code": 200, "tokens": [1, 2, 3],
                     "status": "done"}])
        res3 = sc3.call_with_retry(lambda: next(seq), optin, "k",
                                   sleep=lambda s: None)
        assert res3["attempts"] == 2 and res3["tokens"] == [1, 2, 3]

    def test_retry_rides_a_real_429(self, model):
        """End to end: a burst past max_pending sheds 429s; a retrying
        client wins on a later attempt instead of surfacing the shed."""
        params, cfg = model
        sc = _load_tool("serving_client")
        srv = serve(params, cfg, port=0, batch=1, round_steps=4,
                    max_pending=1, seed=0).start_background()
        try:
            prompts = _prompts(cfg, 8, seed=13)
            policy = sc.RetryPolicy(max_attempts=8, base_delay_s=0.2,
                                    budget_s=120.0)
            results = [None] * 8

            def fire(i):
                results[i] = sc.ServingClient(
                    port=srv.port, timeout=120.0).generate(
                        prompts[i], 8, retry=policy)

            threads = [threading.Thread(target=fire, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert all(r["code"] == 200 for r in results), \
                [(r["code"], r.get("attempts")) for r in results]
            assert any(r["attempts"] > 1 for r in results)  # shed+won
        finally:
            srv.begin_drain(60.0)


# -- the CI form: env-armed subprocess chaos smoke --------------------


class TestChaosSubprocessSmoke:
    def test_fault_injected_server_recovers_and_runlog_is_clean(
            self, tmp_path):
        """The acceptance criterion against a REAL process: a server
        armed via MARLIN_FAULT_PLAN crashes mid-stream, recovers, every
        stream completes byte-identical to an in-process golden,
        /metrics shows exactly one restart, SIGTERM drains clean, and
        the sealed runlog passes the crash-cycle detector."""
        sc = _load_tool("serving_client")
        runlog = tmp_path / "chaos_runlog.jsonl"
        plan = {"specs": [{"site": "decode_round", "round": 4,
                           "action": "raise"}]}
        # The in-process golden below runs under conftest's jax config
        # (x64 + partitionable threefry); the subprocess must match or
        # init_params diverges and the byte-exactness check is vacuous.
        env = dict(os.environ, MARLIN_FAULT_PLAN=json.dumps(plan),
                   JAX_ENABLE_X64="True",
                   JAX_THREEFRY_PARTITIONABLE="true")
        proc = subprocess.Popen(
            [sys.executable, "-m", "marlin_tpu.serving.server",
             "--port", "0", "--force-cpu", "--d-model", "32",
             "--n-layers", "2", "--vocab", "64", "--max-len", "64",
             "--batch", "2", "--round-steps", "2",
             "--runlog", str(runlog)],
            cwd=_REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)
        try:
            line = proc.stdout.readline()
            assert line.startswith("SERVING "), line
            port = int(line.strip().split("port=")[1])
            c = sc.ServingClient(port=port, timeout=120.0)
            warm_prompt = list(range(8))
            warm = c.generate(warm_prompt, 2)
            assert warm["code"] == 200
            # Three concurrent streams long enough to straddle the
            # round-4 crash.
            prompts = _prompts(_cfg(), 3, seed=17)
            results = [None] * 3

            def fire(i):
                results[i] = sc.ServingClient(
                    port=port, timeout=120.0).stream(prompts[i], 24)

            threads = [threading.Thread(target=fire, args=(i,))
                       for i in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            # Byte-exact across the crash: greedy output is a pure
            # function of the prompt (arrival-order invariant), so an
            # in-process golden of the same model settles it. The demo
            # entry builds d_ff = 4*d_model — mirror it exactly.
            cfg = _cfg(d_ff=128)
            params = init_params(cfg, seed=0)
            gold_by_prompt = {}
            geng = ServingEngine(params, cfg, batch=2, round_steps=2,
                                 metrics_registry=MetricsRegistry())
            for p in [warm_prompt] + [list(map(int, p))
                                      for p in prompts]:
                geng.submit(np.asarray(p, np.int32),
                            2 if p == warm_prompt else 24)
            for r in geng.run():
                gold_by_prompt[tuple(map(int, r.prompt))] = \
                    list(map(int, r.tokens))
            assert warm["tokens"] == gold_by_prompt[tuple(warm_prompt)]
            for i, res in enumerate(results):
                assert res["code"] == 200, res
                assert res["status"] == "done" and res["emitted"] == 24
                assert res["tokens"] == \
                    gold_by_prompt[tuple(map(int, prompts[i]))], i
            # Exactly one supervised restart, visible to a scraper.
            samples = c.metrics()["samples"]
            assert samples.get("serving_engine_restarts_total") == 1
            assert samples.get(
                'serving_faults_injected_total{site="decode_round"}'
            ) == 1
            rz = c.readyz()
            assert rz["code"] == 200 and rz["ready"]
            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(60.0)
            assert rc == 0, proc.stderr.read()[-800:]
            assert "DRAINED" in proc.stdout.read()
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(10.0)
        # The sealed runlog passes the crash-cycle detector: the crash
        # is narrated, every interrupted request resolved, zero
        # post-warmup compiles (warm caches across the restart), and
        # the phase-sum identity held for every completion.
        rep = subprocess.run(
            [sys.executable, "tools/runlog_report.py", str(runlog),
             "--json", "-"],
            capture_output=True, text=True, timeout=60, cwd=_REPO)
        assert rep.returncode == 0, rep.stdout + rep.stderr
        report = json.loads(rep.stdout)
        assert report["ok"] is True, report["anomalies"]
        assert report["sealed"] is True
        assert report["n_crashes"] == 1
        assert report["n_recovered"] >= 1
        assert report["n_quarantined"] == 0
        assert report["engine_failed"] is False
        assert report["post_warmup_compiles"] == 0
        assert report["n_completed"] == 4
        assert report["phase_sum_max_rel_err"] <= 0.05
        events = [json.loads(l)
                  for l in runlog.read_text().strip().splitlines()]
        kinds = [e["kind"] for e in events]
        assert "fault_plan" in kinds  # the env arming is on record
        assert kinds[-1] == "drain_complete"
