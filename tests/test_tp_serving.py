"""Tensor-parallel serving: bit-exactness, compile pins, bench gate.

The shard_map TP path (marlin_tpu/models/tp.py + marlin_tpu/serving/
tp.py, docs/serving.md §TP) claims BIT-exactness, not allclose: in
gather mode every output element is one full-width contraction computed
on exactly one device, so TP>1 logits — and therefore sampled tokens,
KV bytes, and whole serving rounds — equal the TP=1 bytes. These tests
pin that claim per layer block, per serving mode, and per compiled-set
size, on the 8-device forced CPU mesh (tests/conftest.py).
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marlin_tpu.models import TransformerConfig, init_params
from marlin_tpu.models import tp as mtp
from marlin_tpu.models.quant import quantize_params_int8
from marlin_tpu.serving import ServingEngine

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(tp=1, rope=False, n_heads=4, n_kv_heads=0, tp_mode="gather"):
    return TransformerConfig(
        vocab=61, d_model=32, n_heads=n_heads, n_kv_heads=n_kv_heads,
        n_layers=2, d_ff=64, max_len=64, rope=rope, tp=tp,
        tp_mode=tp_mode)


# (name, cfg kwargs, int8) — the GQA arm keeps kv_heads divisible by 4
# so the TP=4 arm shards whole KV-head groups (validate_tp's contract).
VARIANTS = [
    ("plain", dict(), False),
    ("rope_gqa", dict(rope=True, n_heads=8, n_kv_heads=4), False),
    ("int8", dict(rope=True, n_heads=8, n_kv_heads=4), True),
]


class TestTPModelBitExact:
    """Seeded property: sharded forward == unsharded at EVERY layer
    boundary (attention residual, MLP residual, logits), TP in {1,2,4},
    across plain / rope+GQA / int8."""

    @pytest.mark.parametrize("name,kw,int8", VARIANTS,
                             ids=[v[0] for v in VARIANTS])
    def test_block_outputs_bitexact(self, rng, name, kw, int8):
        params = init_params(_cfg(**kw), seed=7)
        if int8:
            params = quantize_params_int8(params)
        tok = jnp.asarray(rng.integers(0, 61, (3, 24)), jnp.int32)
        ref_atts, ref_outs, ref_logits = mtp.tp_block_outputs(
            params, tok, _cfg(**kw))
        for tp in (2, 4):
            atts, outs, logits = mtp.tp_block_outputs(
                params, tok, _cfg(tp=tp, **kw))
            np.testing.assert_array_equal(np.asarray(atts),
                                          np.asarray(ref_atts))
            np.testing.assert_array_equal(np.asarray(outs),
                                          np.asarray(ref_outs))
            np.testing.assert_array_equal(np.asarray(logits),
                                          np.asarray(ref_logits))

    def test_psum_mode_is_close_not_exact_contract(self, rng):
        # The OPTIONAL Megatron row-parallel layout halves the
        # collectives but splits the contraction — allclose is its
        # documented contract (docs/serving.md §TP), and the default
        # stays "gather" precisely because serving needs bytes.
        kw = dict(rope=True, n_heads=8, n_kv_heads=4)
        params = init_params(_cfg(**kw), seed=3)
        tok = jnp.asarray(rng.integers(0, 61, (2, 16)), jnp.int32)
        ref = mtp.tp_forward(params, tok, _cfg(**kw))
        got = mtp.tp_forward(params, tok,
                             _cfg(tp=2, tp_mode="psum", **kw))
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6)

    def test_validate_tp_rejects_unsplittable_heads(self):
        with pytest.raises(ValueError, match="must divide"):
            mtp.tp_forward(init_params(_cfg(), seed=0),
                           jnp.zeros((1, 4), jnp.int32),
                           _cfg(tp=4, n_heads=8, n_kv_heads=2))


def _run_engine(params, cfg, prompts, steps, *, paged, spec,
                chunk=None):
    eng = ServingEngine(
        params, cfg, batch=2, round_steps=2, temperature=0.7, seed=0,
        max_pending=4 * len(prompts) + 8,
        kv_pages=16 if paged else None,
        prefill_chunk=chunk,
        spec_draft_lens=(4,) if spec else None)
    got = {}
    for i, p in enumerate(prompts):
        eng.submit(p, steps, request_id=100 + i)
    for r in eng.run():
        got[r.request_id] = list(map(int, r.tokens))
    return eng, got


class TestTPEngineBitExact:
    """Whole serving rounds at TP=2/4 drain byte-identically to TP=1 —
    contiguous, paged, chunked-prefill, and speculative — with the
    compiled set pinned EXACTLY (zero steady-state recompiles)."""

    STEPS = 6

    def _prompts(self, rng, n=4):
        return [rng.integers(1, 61, int(rng.integers(4, 20)))
                .astype(np.int32) for _ in range(n)]

    @pytest.mark.parametrize("mode", ["contig", "paged", "chunked",
                                      "spec_paged"])
    def test_rounds_bitexact_across_tp(self, rng, mode):
        kw = dict(rope=True, n_heads=8, n_kv_heads=4)
        params = init_params(_cfg(**kw), seed=1)
        prompts = self._prompts(rng)
        paged = mode in ("paged", "spec_paged")
        spec = mode == "spec_paged"
        chunk = 16 if mode == "chunked" else (16 if paged else None)
        ref = None
        for tp in (1, 2, 4):
            eng, got = _run_engine(
                params, _cfg(tp=tp, **kw), prompts, self.STEPS,
                paged=paged, spec=spec, chunk=chunk)
            assert len(got) == len(prompts)
            if ref is None:
                ref = got
            else:
                assert got == ref, f"{mode}: tp={tp} diverged from tp=1"

    def test_int8_rounds_bitexact_across_tp(self, rng):
        kw = dict(rope=True, n_heads=8, n_kv_heads=4)
        params = quantize_params_int8(init_params(_cfg(**kw), seed=2))
        prompts = self._prompts(rng)
        ref = None
        for tp in (1, 2):
            _, got = _run_engine(params, _cfg(tp=tp, **kw), prompts,
                                 self.STEPS, paged=True, spec=False,
                                 chunk=16)
            ref = got if ref is None else ref
            assert got == ref

    @pytest.mark.parametrize("paged", [False, True],
                             ids=["contig", "paged"])
    def test_zero_steady_state_recompiles_under_tp(self, rng, paged):
        # Exact compile-count pin: after a warmup wave covering every
        # admission/decode shape bucket, a second wave of fresh
        # requests must add ZERO cache entries to any registered entry
        # point — the watchdog poll IS the count, and the TP wrappers
        # are the registered jits (serving/tp.py module-level).
        kw = dict(rope=True, n_heads=8, n_kv_heads=4)
        params = init_params(_cfg(**kw), seed=4)
        eng = ServingEngine(
            params, _cfg(tp=2, **kw), batch=2, round_steps=2,
            temperature=0.7, seed=0, max_pending=64,
            kv_pages=16 if paged else None,
            prefill_chunk=16 if paged else None)
        for i, p in enumerate(self._prompts(rng)):
            eng.submit(p, self.STEPS, request_id=500 + i)
        eng.run()
        eng.watchdog.poll(rebaseline=True)  # consume warmup compiles
        with eng.watchdog.no_recompiles():
            for i, p in enumerate(self._prompts(rng)):
                eng.submit(p, self.STEPS, request_id=600 + i)
            eng.run()

    def test_contiguous_prefix_cache_gated_at_tp(self):
        from marlin_tpu.serving import PrefixCache

        kw = dict(rope=True, n_heads=8, n_kv_heads=4)
        params = init_params(_cfg(**kw), seed=0)
        with pytest.raises(NotImplementedError, match="PAGED"):
            ServingEngine(params, _cfg(tp=2, **kw), batch=2,
                          prefix_cache=PrefixCache(_cfg(tp=2, **kw),
                                                   pool_rows=4))

    def test_engine_surfaces_tp_degree(self, rng):
        kw = dict(rope=True, n_heads=8, n_kv_heads=4)
        params = init_params(_cfg(**kw), seed=0)
        eng = ServingEngine(params, _cfg(tp=2, **kw), batch=2,
                            kv_pages=16, prefill_chunk=16)
        snap = eng.debug_snapshot()
        assert snap["tp_degree"] == 2
        assert snap["tp_mode"] == "gather"


class TestTPBenchSmoke:
    def test_bench_serving_tp_line_and_slo_gate(self, tmp_path):
        """`bench.py --config serving_tp` end to end at default knobs:
        modeled per-device FLOP scaling >= the committed 3.5x floor at
        TP=4 (cost_model.tp_decode_flop_scaling at the reference
        shape), engine bit-exactness across TP=1/2/4, recompile zeros,
        and the TP=2 worker-group fleet's drain-under-load with zero
        dropped accepted requests — then tools/slo_check.py against
        the committed metrics_serving_tp block."""
        env = dict(os.environ, BENCH_FORCE_CPU="1", BENCH_RETRIES="1")
        r = subprocess.run(
            [sys.executable, "bench.py", "--config", "serving_tp"],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=_REPO)
        assert r.returncode == 0, r.stderr[-800:]
        lines = [json.loads(l) for l in r.stdout.strip().splitlines()]
        (line,) = [d for d in lines
                   if d["metric"] == "serving_tp_scaling"]
        assert line["bitexact"] is True
        assert line["recompiles_after_warmup"] == 0
        assert line["value"] >= 3.5
        assert line["fleet_drain_under_load_ok"] is True
        assert line["fleet_responses_bitexact"] is True
        assert line["fleet_dropped_accepted"] == 0
        assert line["fleet_tp_degree"] == 2
        artifact = tmp_path / "tp_artifact.jsonl"
        artifact.write_text(r.stdout)
        slo = subprocess.run(
            [sys.executable, "tools/slo_check.py", str(artifact),
             "--metrics-key", "metrics_serving_tp"],
            capture_output=True, text=True, timeout=60, cwd=_REPO)
        assert slo.returncode == 0, slo.stdout + slo.stderr
        assert "SLO OK" in slo.stdout

    def test_modeled_scaling_floor_fast(self):
        # The gated quantity itself, without the bench harness: the
        # committed layout's Amdahl number at the reference shape must
        # clear the baseline floor (pure cost model, milliseconds).
        from benchlib.configs_tp import _REF_SHAPE
        from marlin_tpu.utils.cost_model import tp_decode_flop_scaling

        ref = TransformerConfig(
            d_ff=4 * _REF_SHAPE["d_model"], rope=True,
            dtype="bfloat16", **_REF_SHAPE)
        s2 = tp_decode_flop_scaling(ref, batch=8, tp=2)
        s4 = tp_decode_flop_scaling(ref, batch=8, tp=4)
        assert 1.8 <= s2 <= 2.0
        assert 3.5 <= s4 <= 4.0
        # Per-device cost at tp=1 is the base model exactly.
        from marlin_tpu.utils.cost_model import (decode_step_cost,
                                                 tp_decode_step_cost)
        assert tp_decode_step_cost(ref, 8, tp=1) \
            == decode_step_cost(ref, 8)
