"""L0 local-kernel tests — mirrors LocalMatrixSuite's golden 4x4 pattern
(src/test/scala/.../LocalMatrixSuite.scala:8-72): CSC conversion and the three
multiply kernels against hand-written expected matrices."""

import numpy as np
import pytest

from marlin_tpu.matrix.local import (
    DenseMatrix,
    DenseVector,
    Matrices,
    SparseMatrix,
    SparseVector,
    Vectors,
    dspr,
    mult_dense_sparse,
    mult_sparse_dense,
    triu_to_full,
)

# Golden 4x4 fixtures, hand-checked.
S = np.array(
    [
        [1.0, 0.0, 0.0, 2.0],
        [0.0, 0.0, 3.0, 0.0],
        [0.0, 4.0, 0.0, 0.0],
        [5.0, 0.0, 0.0, 6.0],
    ]
)
D = np.array(
    [
        [1.0, 2.0, 3.0, 4.0],
        [4.0, 3.0, 2.0, 1.0],
        [1.0, 1.0, 1.0, 1.0],
        [2.0, 0.0, 2.0, 0.0],
    ]
)


class TestCSCConversion:
    def test_from_to_dense(self):
        sm = SparseMatrix.from_dense(S)
        assert sm.nnz == 6
        np.testing.assert_allclose(sm.to_dense(), S)
        # CSC layout golden check: column pointers count 2,1,1,2 nnz per col.
        np.testing.assert_array_equal(sm.col_ptrs, [0, 2, 3, 4, 6])
        np.testing.assert_array_equal(sm.row_indices, [0, 3, 2, 1, 0, 3])

    def test_rand_sparsity(self):
        sm = SparseMatrix.rand(50, 50, 0.1, seed=1)
        assert 0.04 < sm.nnz / 2500 < 0.16


class TestMultiplyKernels:
    def test_sparse_x_sparse_golden(self):
        a = SparseMatrix.from_dense(S)
        b = SparseMatrix.from_dense(S.T)
        out = a.multiply(b)
        np.testing.assert_allclose(out.to_dense(), S @ S.T)

    def test_dense_x_sparse_golden(self):
        np.testing.assert_allclose(
            mult_dense_sparse(D, SparseMatrix.from_dense(S)), D @ S
        )

    def test_dense_x_sparse_copy_shortcut(self):
        # A singleton 1.0 column triggers the copy shortcut
        # (LibMatrixMult.scala:15-41).
        s = np.zeros((4, 3))
        s[2, 1] = 1.0
        s[0, 0] = 2.0
        np.testing.assert_allclose(
            mult_dense_sparse(D, SparseMatrix.from_dense(s)), D @ s
        )

    def test_sparse_x_dense_golden(self):
        np.testing.assert_allclose(
            mult_sparse_dense(SparseMatrix.from_dense(S), D), S @ D
        )

    def test_dimension_mismatch(self):
        with pytest.raises(ValueError):
            SparseMatrix.from_dense(S).multiply(SparseMatrix.from_dense(S[:3]))


class TestLocalDense:
    def test_column_major(self):
        m = Matrices.dense(2, 3, [1, 2, 3, 4, 5, 6])
        np.testing.assert_allclose(m.to_numpy(), [[1, 3, 5], [2, 4, 6]])
        assert m(1, 2) == 6
        back = Matrices.from_numpy(m.to_numpy())
        np.testing.assert_allclose(back.values, m.values)


class TestVectors:
    def test_dense_ops(self):
        a = Vectors.dense(1.0, 2.0, 3.0)
        b = Vectors.dense([4.0, 5.0, 6.0])
        np.testing.assert_allclose(a.add(b).values, [5, 7, 9])
        np.testing.assert_allclose(b.subtract(a).values, [3, 3, 3])
        assert a.dot(b) == 32

    def test_sparse_vector(self):
        s = Vectors.sparse(5, [1, 3], [2.0, 4.0])
        np.testing.assert_allclose(s.to_numpy(), [0, 2, 0, 4, 0])
        with pytest.raises(ValueError):
            Vectors.sparse(3, [5], [1.0])

    def test_binary_serialization_roundtrip(self):
        # The Writable write/readFields analogue (Vectors.scala:174-187).
        d = Vectors.dense(1.5, -2.5)
        assert Vectors.from_bytes(d.to_bytes()) == d
        s = Vectors.sparse(10, [0, 9], [1.0, 2.0])
        back = Vectors.from_bytes(s.to_bytes())
        assert isinstance(back, SparseVector)
        np.testing.assert_allclose(back.to_numpy(), s.to_numpy())


class TestPackedKernels:
    def test_dspr_and_triu_to_full(self):
        n = 4
        rng = np.random.default_rng(0)
        packed = np.zeros(n * (n + 1) // 2)
        x1, x2 = rng.standard_normal(n), rng.standard_normal(n)
        dspr(1.0, x1, packed)
        dspr(0.5, x2, packed)
        expected = np.outer(x1, x1) + 0.5 * np.outer(x2, x2)
        np.testing.assert_allclose(triu_to_full(n, packed), expected, rtol=1e-12)
