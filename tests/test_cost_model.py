"""Static cost harness (VERDICT r04 item 4): the tunnel-independent perf
floor.

Each hot path's compiled program is held to its analytic roofline model via
XLA's cost/memory analysis — on the CPU mesh, with no hardware in the loop.
A perf regression (a gather turning dense, chunked CE materializing logits,
decode re-reading the cache, an attention clamp change silently moving the
ceiling) fails HERE, tunnel or no tunnel; the chip's job shrinks to
confirming achieved fractions of these modeled rooflines."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import marlin_tpu as mt
from marlin_tpu.utils import cost_model as cm


@pytest.fixture(scope="module")
def mesh():
    return mt.create_mesh()


class TestCompiledCost:
    def test_local_gemm_flops_exact(self):
        m, k, n = 256, 128, 512
        a = jnp.ones((m, k), jnp.float32)
        b = jnp.ones((k, n), jnp.float32)
        rep = cm.compiled_cost(lambda a, b: a @ b, a, b)
        flops, byts = cm.gemm_cost(m, k, n)
        assert rep.flops == flops  # XLA counts dot MACs as 2 flops, exactly
        # Operands + output each cross memory once; fusion may add a small
        # factor but a 2x blowout means an extra materialization.
        assert byts <= rep.bytes_accessed <= 2 * byts

    def test_summa_per_device_flops(self, mesh):
        from marlin_tpu.config import get_config
        from marlin_tpu.parallel import summa

        cfg = get_config()
        pr, pc = mt.mesh.axis_sizes(mesh)
        m = k = n = 64 * pr * pc
        a = jnp.ones((m, k), jnp.float32)
        b = jnp.ones((k, n), jnp.float32)
        fn = summa._summa_fn(mesh, "default", cfg.mesh_axis_rows,
                             cfg.mesh_axis_cols)
        rep = cm.compiled_cost(fn, a, b)
        flops, byts = cm.summa_cost(m, k, n, pr, pc)
        # SPMD cost analysis is per-device; the local matmul dominates.
        assert rep.flops == pytest.approx(flops, rel=0.01)
        # Bytes include the all-gathered panels; the gather's own
        # source+destination accounting lands within a small factor.
        assert byts <= rep.bytes_accessed <= 4 * byts


class TestEllProductCost:
    """The low-density arm's reason to exist: traffic ~ nnz * n, not m*k*n."""

    def _ell_compiled(self, m, k, n, density, mesh):
        from marlin_tpu.matrix.dist_sparse import (DistSparseVecMatrix,
                                                   _ell_product, _n_dev)
        from marlin_tpu.mesh import row_sharding

        rng = np.random.default_rng(3)
        nnz = int(m * k * density)
        r = rng.integers(0, m, nnz)
        c = rng.integers(0, k, nnz)
        v = rng.standard_normal(nnz)
        a = DistSparseVecMatrix.from_coo(r, c, v, (m, k))
        ec, ev, r_slots = a.ell_stripes()
        nd = _n_dev(mesh)
        b = jax.device_put(jnp.ones((a.stripe * nd, n), ev.dtype),
                           row_sharding(a.mesh))
        fn = _ell_product(a.mesh, nd, a.stripe, r_slots, n,
                          jnp.dtype(ev.dtype))
        return cm.compiled_cost(fn, ec, ev, b), a.stripe, r_slots, nd

    def test_flops_track_slots_not_density_squared(self, mesh):
        m = k = 512
        n = 256
        rep, stripe, r_slots, nd = self._ell_compiled(m, k, n, 2e-3, mesh)
        flops, byts = cm.ell_product_cost(stripe * nd, k, n, r_slots, nd)
        dense_flops = 2.0 * (stripe * nd / nd) * k * n  # per-device ring arm
        # The model counts the multiply+reduce; XLA adds the gather/select
        # overhead around it — band, not equality.
        assert rep.flops <= 4 * flops + 1e5
        # The point of the arm: far under the dense ring's MXU cost.
        assert rep.flops < 0.25 * dense_flops
        assert rep.bytes_accessed < 6 * byts

    def test_cost_scales_with_slots(self, mesh):
        # Double the density -> slots (and modeled cost) roughly double;
        # the compiled program must follow, not stay dense-sized.
        m = k = 512
        n = 256
        lo, *_ = self._ell_compiled(m, k, n, 1e-3, mesh)
        hi, *_ = self._ell_compiled(m, k, n, 8e-3, mesh)
        assert hi.flops > 2 * lo.flops


class TestDecodeCost:
    def _cfg(self, **kw):
        from marlin_tpu.models.transformer import TransformerConfig

        base = dict(vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
                    max_len=64)
        base.update(kw)
        return TransformerConfig(**base)

    def test_param_count_matches_init_exactly(self):
        from marlin_tpu.models.transformer import init_params

        for kw in ({}, {"rope": True}, {"n_kv_heads": 2},
                   {"n_experts": 4}):
            cfg = self._cfg(**kw)
            p = init_params(cfg, seed=0)
            got = sum(x.size for x in jax.tree.leaves(p))
            assert got == cm.transformer_param_count(cfg), kw

    def test_int8_param_pricing_matches_quantized_pytree_exactly(self):
        # The advisor-r05 fix: the int8 arm's predicted bytes must AGREE
        # with the bench roofline denominator, which prices actual pytree
        # leaves — int8 leaves at 1 byte, every float leaf (biases, norms,
        # the s8 scales _cast_params casts once) at the compute itemsize.
        # Held to EXACT equality against a real quantized pytree.
        from marlin_tpu.models import quantize_params_int8
        from marlin_tpu.models.transformer import init_params

        for kw in ({}, {"rope": True}, {"n_kv_heads": 2}):
            cfg = self._cfg(**kw)
            p = quantize_params_int8(init_params(cfg, seed=0))
            it = 2  # bf16 compute dtype
            want = sum(
                leaf.nbytes if jnp.issubdtype(leaf.dtype, jnp.integer)
                else leaf.size * it for leaf in jax.tree.leaves(p))
            q_elems, n_scales = cm.quantized_weight_counts(cfg)
            total = cm.transformer_param_count(cfg)
            got = q_elems + (n_scales + total - q_elems) * it
            assert got == want, kw

    def test_int8_cache_pricing_matches_bench_per_vec(self):
        # Cache side of the same agreement: decode_step_cost under
        # kv_quant must charge exactly the bench roofline's
        # per_vec = dh + 4 bytes per stored K/V vector (int8 slots + one
        # f32 scale), read once plus the 1/cache_len write-back share.
        cfg = self._cfg(kv_quant="int8")
        batch = 4
        dh = cfg.d_model // cfg.n_heads
        _, byts = cm.decode_step_cost(cfg, batch, param_itemsize=2,
                                      cache_itemsize=2, quant_weights=True)
        kv_heads = cfg.kv_heads
        per_seq = 2 * cfg.n_layers * cfg.max_len * kv_heads * (dh + 4)
        q_elems, n_scales = cm.quantized_weight_counts(cfg)
        total = cm.transformer_param_count(cfg)
        p_bytes = q_elems + (n_scales + total - q_elems) * 2
        want = p_bytes + batch * per_seq * (1 + 1 / cfg.max_len)
        assert byts == pytest.approx(want, rel=1e-9)
        # And the write-back share is the only thing separating the model
        # from the roofline's read-side denominator.
        assert byts - (p_bytes + batch * per_seq) \
            == pytest.approx(batch * per_seq / cfg.max_len, rel=1e-9)

    def test_decode_step_streams_params_and_cache_once(self):
        from marlin_tpu.models import transformer as tr

        cfg = self._cfg()
        p = tr.init_params(cfg, seed=0)
        batch = 4
        cache = tr.init_kv_cache(cfg, batch)
        tok = jnp.zeros((batch,), jnp.int32)
        fn = jax.jit(tr.decode_step, static_argnames="cfg")
        rep = cm.compiled_cost(fn, p, cache, tok, 3, cfg=cfg)
        flops, byts = cm.decode_step_cost(cfg, batch)
        # Decode is HBM-bound: everything the step touches is params +
        # cache (read once, one-slot write) + activations of order B*D.
        # XLA's per-instruction accounting on the unfused CPU pipeline
        # lands at ~3.7x the perfect-reuse model (calibrated here); one
        # EXTRA cache or params pass (+0.9x model) breaks the band.
        assert byts <= rep.bytes_accessed <= 4.5 * byts
        assert flops <= rep.flops <= 3 * flops
        # The temp arena must hold activations, not a second cache copy.
        cache_bytes = sum(x.nbytes for lay in cache for x in lay.values())
        assert rep.temp_bytes <= 2.5 * cache_bytes


class TestChunkedCECost:
    # ~20 s of large-vocab compiles — tier-1 wall-clock budget
    # (ROADMAP 9) moves it under -m slow.
    @pytest.mark.slow
    def test_grad_temp_arena_does_not_scale_with_vocab(self, monkeypatch):
        """The chunked-CE contract, stated as memory accounting: the grad's
        temp arena must be VOCAB-INDEPENDENT (per-chunk logits live only
        inside the lax.map body under jax.checkpoint), while the unchunked
        control grows by full (B*S, vocab) buffers — so a regression that
        starts materializing logits moves the measured arena by megabytes."""
        from marlin_tpu.models import transformer as tr

        def temp(vocab, chunk):
            cfg = tr.TransformerConfig(vocab=vocab, d_model=32, n_heads=2,
                                       n_layers=1, d_ff=64, max_len=128)
            p = tr.init_params(cfg, seed=0)
            tok = jnp.zeros((2, 128), jnp.int32)
            monkeypatch.setattr(tr, "_CE_CHUNK", chunk)
            grad = jax.jit(jax.grad(tr.loss_fn), static_argnames="cfg")
            return cm.compiled_cost(grad, p, tok, tok, cfg=cfg).temp_bytes

        b, s = 2, 128
        delta_logits = cm.ce_logits_bytes(b, s, 2048) \
            - cm.ce_logits_bytes(b, s, 512)
        chunked_512, chunked_2048 = temp(512, 32), temp(2048, 32)
        # Vocab x4 moves the chunked arena by at most one chunk's buffers.
        assert abs(chunked_2048 - chunked_512) <= \
            4 * cm.ce_logits_bytes(1, 32, 2048)
        # Control (the test's teeth): the unchunked path pays full
        # logits-sized buffers — >= two (forward value + backward
        # cotangent) on current XLA, >= one on jax 0.4.x whose CPU
        # allocator buffer-shares more aggressively; either way the arena
        # grows with vocab by at least a full logits buffer while the
        # chunked arena (asserted above) moves by at most a chunk's worth.
        direct_512, direct_2048 = temp(512, b * s), temp(2048, b * s)
        assert direct_2048 - direct_512 >= delta_logits
        assert chunked_2048 < direct_2048


class TestAttentionBlockModel:
    """The Pallas kernel is a custom call XLA's tables can't see into, so
    its model is grid accounting locked to the kernel's OWN predicates."""

    def test_python_predicate_matches_kernel_predicate(self):
        import importlib

        fa = importlib.import_module("marlin_tpu.ops.flash_attention")

        for bq, bk in ((256, 128), (512, 512), (1024, 1024)):
            for w in (0, 256, 1024):
                for i in range(0, 9):
                    for j in range(0, 9):
                        want = bool(fa._block_live(
                            i, j, causal=True, block_q=bq, block_k=bk,
                            window=w))
                        got = cm._py_block_live(
                            i, j, causal=True, block_q=bq, block_k=bk,
                            window=w)
                        assert got == want, (bq, bk, w, i, j)

    def test_windowed_sweep_matches_kernel_bounds(self):
        import importlib

        fa = importlib.import_module("marlin_tpu.ops.flash_attention")

        s, bq, bk, w = 8192, 512, 512, 1024
        n_k = s // bk
        counts = cm.attention_block_counts(s, bq, bk, window=w)
        # The model's per-i sweep must be exactly the kernel's shrunk grid.
        span = fa._win_kblocks(n_k, block_q=bq, block_k=bk, window=w)
        visited = 0
        for i in range(s // bq):
            lo = int(fa._win_lo_k(i, block_q=bq, block_k=bk, window=w))
            visited += min(lo + span, n_k) - lo
        assert counts["visited"] == visited

    def test_ceilings_reproduce_r04_derivation(self):
        # docs/ROUND4.md §7: at the w/2 clamp (512, 512) the ceiling is
        # ~2.25x (the r03 2.27x measurement sat AT it, not 35% under a
        # mistaken 8x bar); the small-block sweep points reach 3.0-3.27x.
        assert cm.speedup_ceiling(8192, 1024, (512, 512)) == pytest.approx(
            2.25, abs=0.2)
        assert cm.speedup_ceiling(8192, 1024, (256, 128)) >= 3.1
        assert cm.speedup_ceiling(8192, 1024, (256, 256)) >= 2.9

    def test_bench_ceiling_evaluates_at_kernel_clamp(self):
        # The bench's windowed ceiling must be computed at the blocks the
        # kernel will actually run — shared helper, not a hand mirror
        # (review finding r05).
        from marlin_tpu.ops.flash_attention import window_block_clamp

        assert window_block_clamp(1024, 1024, 1024) == (512, 512)
        assert window_block_clamp(256, 128, 1024) == (256, 128)  # under cap
        assert window_block_clamp(1024, 1024, 256) == (256, 128)  # floors

    def test_transformer_step_flops_attention_term(self):
        # 6*N*T plus the flash grid's live-block MACs x 3.5 (fwd+bwd); at
        # the bench's long-seq shape the attention term must be material
        # (the understatement the r04 verdict flagged), and a window must
        # shrink it.
        n_params, b, s, L, h, dh = 125_000_000, 1, 8192, 8, 8, 128
        base = 6.0 * n_params * b * s
        full = cm.transformer_step_flops(n_params, b, s, L, h, dh)
        attn = full - base
        assert 0.1 * base < attn < base  # material, not dominant
        win = cm.transformer_step_flops(n_params, b, s, L, h, dh,
                                        window=1024)
        assert win < full and win > base
        # Short sequences: blocks clamp to the padded length (the kernel's
        # effective_blocks), so the attention term can't count a full
        # 1024^2 tile for a 128-position sequence.
        tiny = cm.transformer_step_flops(1000, 1, 128, 1, 2, 32)
        assert tiny - 6.0 * 1000 * 128 == 3.5 * (4.0 * 2 * 32 * 128 * 128)

    def test_ring_hop_bound_is_tight_against_brute_force(self):
        # ring_hops is THE engine function (parallel/ring.py); check it
        # against an independent derivation: the number of consecutive
        # stripes (current + earlier) that can contain keys in any local
        # query's (q - w, q] band.
        from marlin_tpu.parallel.ring import ring_hops

        for n_dev in (4, 8):
            for stripe in (64, 128, 192):
                for w in (1, 63, 64, 65, 128, 300, 10_000):
                    need = 0
                    for i in range(n_dev):
                        for q in range(i * stripe, (i + 1) * stripe):
                            lo_key = max(0, q - w + 1)
                            need = max(need, i - lo_key // stripe + 1)
                    got = ring_hops(n_dev, stripe, w)
                    # The formula is exact (worst query is the stripe's
                    # first position), so no slack: an off-by-one hop
                    # overcount would double ICI at hops=2 configs.
                    assert got == min(n_dev, need), \
                        (n_dev, stripe, w, need, got)

    def test_ring_attention_cost_shapes(self):
        s, h, d, nd = 8192, 8, 128, 8
        full_f, full_b = cm.ring_attention_cost(s, h, d, nd)
        # Causal full ring: live stripe pairs = lower triangle.
        stripe = s // nd
        assert full_f == 4.0 * h * d * stripe * stripe * 36 / 8
        assert full_b == 2.0 * 7 * stripe * h * d * 2
        # A window covering one stripe cuts hops (and ICI bytes) hard.
        win_f, win_b = cm.ring_attention_cost(s, h, d, nd, window=stripe)
        # hops=2 of 8: ICI drops to 1/7 of the full ring's; live stripe
        # pairs drop to 15/36 (the first stripe has no predecessor).
        assert win_b == full_b / 7
        assert win_f == full_f * 15 / 36
        # GQA: rotating stripes carry only the kv heads.
        _, gqa_b = cm.ring_attention_cost(s, h, d, nd, kv_heads=2)
        assert gqa_b == full_b * 2 / h
        # Invalid engine combination must not return fabricated numbers.
        with pytest.raises(ValueError, match="causal"):
            cm.ring_attention_cost(s, h, d, nd, window=64, causal=False)

    def test_flash_cost_flops_formula(self):
        # Causal full-band: live pairs = lower-triangle blocks; the FLOP
        # model must agree with the closed form 4*H*D * S*(S+bq)/2 within
        # the block-rounding margin.
        s, h, d, bq, bk = 4096, 8, 128, 512, 512
        flops, byts = cm.flash_attention_cost(s, h, d, bq, bk, causal=True)
        closed = 4.0 * h * d * s * (s + bq) / 2
        assert flops == pytest.approx(closed, rel=1e-6)
        # Bytes scale with visited blocks: the windowed grid at w=1024 must
        # move far fewer bytes than the causal sweep.
        _, byts_w = cm.flash_attention_cost(s, h, d, bq, bk, window=1024)
        assert byts_w < 0.6 * byts


class TestAdmissionCostModel:
    """The serving admission model's hit-length term (PR 4; priced into
    EngineStats.reclaimed_prefill_flops — the deeper behavioral checks
    live in tests/test_prefix_cache.py next to the engine they price)."""

    def _cfg(self):
        from marlin_tpu.models import TransformerConfig

        return TransformerConfig(vocab=64, d_model=32, n_heads=2,
                                 n_layers=2, d_ff=64, max_len=256)

    def test_cold_admission_scales_with_prompt(self):
        cfg = self._cfg()
        f1, _ = cm.admission_cost(cfg, 64)
        f2, _ = cm.admission_cost(cfg, 128)
        assert f2 > 2 * f1  # superlinear: matmul term + attention triangle

    def test_hit_zero_is_the_cold_cost(self):
        cfg = self._cfg()
        assert cm.admission_cost(cfg, 96) == cm.admission_cost(
            cfg, 96, hit_len=0)


class TestFactorTrendPrograms:
    def test_factor_sweep_programs_compile_early(self):
        # Deliberately EARLY in the suite (this module sorts near the
        # front): one reps=1 pass of each factor sweep compiles the
        # blocked LU panel / Cholesky core programs at the grid shapes
        # into the process-global jit cache, so the real sweep fixtures
        # in tests/test_trend_sweep.py (which run ~650 tests later in
        # tier-1's single-core process) measure CACHE-HIT dispatches
        # instead of paying fresh LLVM compiles at hour N — a late
        # backend_compile of exactly these programs segfaulted XLA CPU
        # once in a full-suite run; fresh/short processes never have.
        for sweep in (cm.run_lu_trend_sweep(reps=1),
                      cm.run_cholesky_trend_sweep(reps=1)):
            assert len(sweep) == 3
            for p in sweep:
                assert p["measured"] > 0 and p["predicted"] > 0


class TestCostCalibration:
    """The in-production drift ledger (cost_model.CostCalibration): the
    trend sweeps validate the models offline, this confronts them with
    measured wall-clock per op class and reports EWMA drift vs a
    warmup-calibrated baseline (docs/observability.md §7)."""

    def test_steady_samples_pin_drift_at_one(self):
        cal = cm.CostCalibration(warmup=3)
        for _ in range(20):
            cal.record("decode", 1e6, 0.002)
        assert cal.drift("decode") == pytest.approx(1.0)
        assert cal.sec_per_unit("decode") == pytest.approx(2e-9)
        s = cal.summary()["decode"]
        assert s["samples"] == 20 and s["drift_ratio"] == 1.0

    def test_sustained_slowdown_moves_drift(self):
        cal = cm.CostCalibration(alpha=0.5, warmup=2)
        for _ in range(4):
            cal.record("decode", 1e6, 0.001)
        for _ in range(10):
            cal.record("decode", 1e6, 0.003)  # model now 3x off
        assert cal.drift("decode") == pytest.approx(3.0, rel=0.05)

    def test_baseline_is_median_of_warmup_window(self):
        # One GC hiccup inside the warmup window must not become the
        # reference: the median keeps the baseline at the normal rate.
        cal = cm.CostCalibration(warmup=5)
        for m in (0.001, 0.001, 0.050, 0.001, 0.001):
            cal.record("decode", 1e6, m)
        for _ in range(10):
            cal.record("decode", 1e6, 0.001)
        assert cal.drift("decode") == pytest.approx(1.0, rel=0.1)

    def test_nonpositive_samples_dropped_and_unknown_op_is_one(self):
        cal = cm.CostCalibration()
        cal.record("decode", 0.0, 0.01)   # all-idle round: no ratio
        cal.record("decode", 1e6, 0.0)
        assert cal.summary() == {}
        assert cal.drift("nope") == 1.0
        assert cal.sec_per_unit("nope") is None

    def test_registry_mirror_exports_drift_gauge(self):
        from marlin_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        cal = cm.CostCalibration(warmup=1, registry=reg)
        cal.record("copy", 1e3, 0.001)
        cal.record("copy", 1e3, 0.002)
        snap = reg.snapshot()
        assert snap["gauges"]['cost_model_drift_ratio{op="copy"}'] \
            == pytest.approx(cal.drift("copy"))

    def test_validation(self):
        with pytest.raises(ValueError, match="alpha"):
            cm.CostCalibration(alpha=0.0)
        with pytest.raises(ValueError, match="warmup"):
            cm.CostCalibration(warmup=0)


class TestEllDensityDerivation:
    """derive_ell_density_max: the data-backed form of
    MarlinConfig.sparse_ell_density_max (ROADMAP item 2 remainder)."""

    def test_interpolates_the_ratio_one_crossing(self):
        pts = [{"density": 1e-3, "ell_over_dense": 0.25},
               {"density": 1e-2, "ell_over_dense": 0.5},
               {"density": 1e-1, "ell_over_dense": 4.0}]
        d = cm.derive_ell_density_max(pts)
        assert 1e-2 < d < 1e-1
        # log-log interpolation: ratio 0.5 -> 4.0 crosses 1 a third of
        # the way through the log-density span (log2: -1 -> 2).
        assert d == pytest.approx(10 ** (-2 + 1 / 3), rel=1e-6)

    def test_clamps_when_one_arm_wins_everywhere(self):
        ell = [{"density": 1e-3, "ell_over_dense": 0.2},
               {"density": 1e-2, "ell_over_dense": 0.8}]
        assert cm.derive_ell_density_max(ell) == 1e-2
        dense = [{"density": 1e-3, "ell_over_dense": 1.5}]
        assert cm.derive_ell_density_max(dense) == 5e-4

    def test_rejects_degenerate_input(self):
        with pytest.raises(ValueError, match="empty"):
            cm.derive_ell_density_max([])
        with pytest.raises(ValueError, match="positive"):
            cm.derive_ell_density_max(
                [{"density": 1e-3, "ell_over_dense": 0.0}])


class TestSvdLocalEigsDerivation:
    """derive_svd_local_eigs_max: the data-backed form of
    MarlinConfig.svd_local_eigs_max (ROADMAP item 8), same derivation
    contract as the ELL density constant above."""

    def test_interpolates_the_ratio_one_crossing(self):
        pts = [{"n": 256, "local_over_dist": 0.25},
               {"n": 512, "local_over_dist": 0.5},
               {"n": 1024, "local_over_dist": 2.0}]
        d = cm.derive_svd_local_eigs_max(pts)
        # log-log interpolation: ratio 0.5 -> 2.0 crosses 1 exactly
        # halfway through the log-n span 512 -> 1024.
        assert d == round(512 * 2 ** 0.5)

    def test_clamps_when_one_arm_wins_everywhere(self):
        local = [{"n": 256, "local_over_dist": 0.5},
                 {"n": 512, "local_over_dist": 0.9}]
        assert cm.derive_svd_local_eigs_max(local) == 512
        dist = [{"n": 128, "local_over_dist": 1.5}]
        assert cm.derive_svd_local_eigs_max(dist) == 64

    def test_points_need_not_be_sorted(self):
        pts = [{"n": 1024, "local_over_dist": 2.0},
               {"n": 256, "local_over_dist": 0.5}]
        assert cm.derive_svd_local_eigs_max(pts) == \
            cm.derive_svd_local_eigs_max(list(reversed(pts)))

    def test_rejects_degenerate_input(self):
        with pytest.raises(ValueError, match="empty"):
            cm.derive_svd_local_eigs_max([])
        with pytest.raises(ValueError, match="positive"):
            cm.derive_svd_local_eigs_max(
                [{"n": 128, "local_over_dist": 0.0}])
