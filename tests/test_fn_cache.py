"""utils.fn_cache: compiled-program caching ridden on the user callable."""

from marlin_tpu.utils.fn_cache import cached_on


def test_memoizes_per_callable_and_key():
    def f(x):
        return x

    calls = []

    def build():
        calls.append(1)
        return object()

    a = cached_on(f, ("ns", 1), build)
    b = cached_on(f, ("ns", 1), build)
    assert a is b and len(calls) == 1
    c = cached_on(f, ("ns", 2), build)
    assert c is not a and len(calls) == 2


def test_namespaces_share_one_dict_without_collision():
    def f(x):
        return x

    a = cached_on(f, ("ep", 4), lambda: "expert")
    b = cached_on(f, ("pp", 4), lambda: "pipeline")
    assert (a, b) == ("expert", "pipeline")
    assert set(f._marlin_compiled) == {("ep", 4), ("pp", 4)}


def test_cache_dies_with_the_callable():
    import gc
    import weakref

    def make():
        def f(x):
            return x
        return f

    f = make()
    token = object()
    cached_on(f, ("k",), lambda: token)
    ref = weakref.ref(f)
    del f
    gc.collect()
    assert ref() is None  # nothing pins the callable (or its closure)


def test_no_dict_callables_fall_back_to_uncached():
    calls = []

    def build():
        calls.append(1)
        return len(calls)

    # Bound methods have no __dict__ to ride (partials do in CPython).
    m = ("x").__len__
    assert cached_on(m, ("k",), build) == 1
    assert cached_on(m, ("k",), build) == 2  # rebuilt: no __dict__ to ride


class TestHwDetection:
    """utils.hw.is_tpu: the axon platform string must not defeat detection
    (the bug that once ran the flash kernel in interpret mode ON the TPU)."""

    class _Dev:
        def __init__(self, platform, kind):
            self.platform = platform
            self.device_kind = kind

    def test_axon_platform_with_tpu_kind_detected(self):
        from marlin_tpu.utils.hw import is_tpu

        assert is_tpu(self._Dev("axon", "TPU v5 lite"))
        assert is_tpu(self._Dev("tpu", "TPU v4"))
        assert not is_tpu(self._Dev("cpu", "cpu"))
        assert not is_tpu(self._Dev("gpu", "NVIDIA H100"))

    def test_default_device_path(self):
        # On the CPU test mesh the default device is not a TPU.
        from marlin_tpu.utils.hw import is_tpu

        assert is_tpu() is False
