"""Speculative serving-round suite (docs/serving.md §7, ROADMAP 15):
``ServingEngine(spec_draft_lens=...)`` — per-row draft+verify rounds
with acceptance-adaptive draft length.

The acceptance claims, each pinned mechanically:

* EXACTNESS — greedy outputs are BIT-exact vs the non-spec engine AND
  vs a B=1 ``generate`` run, on the contiguous and the paged cache,
  for plain / rope+GQA / int8-KV configs, with and without eos.
  Speculation is a schedule optimization; it may never move a token.
* SAMPLED INVARIANCE — with ``spec_adaptive=False`` (fixed draft
  length), a sampled request's tokens are a pure function of
  ``(prompt, steps, seed, request_id)``: arrival order, batch shape,
  and wave splits cannot move them. (Distribution-exactness of the
  draft+verify sampler itself is pinned at kernel level —
  test_speculative.py's sampled-spec distribution test.)
* LEDGER — ``emitted == 1 + live_iters + spec_accepted`` holds
  per-request exactly: every token is billed once, either to a decode
  iteration the row was live for or to an accepted draft.
* COMPILE BUDGET — the SET of draft lengths is the whole compile
  cost: a fresh engine compiles exactly ``len(spec_draft_lens)``
  spec-round executables (prewarmed at init), and adaptive draft-
  length switches, second engines, and full workloads add ZERO.
* CRASH RECOVERY — a mid-stream crash under the supervised frontend
  recovers bit-exactly with the spec knobs carried to the successor
  (the test_faults.py contract extended to the spec round).
* SLO GATE — ``bench.py --config serving_spec`` on the committed tiny
  checkpoint (data/tiny_lm) clears the 1.5x tokens/s floor at real
  measured acceptance, TTFT unharmed, zero recompiles in both arms —
  checked end-to-end against the committed baseline's
  ``metrics_spec`` block (tools/slo_check.py --metrics-key).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from marlin_tpu.models import TransformerConfig, generate, init_params
from marlin_tpu.obs.metrics import MetricsRegistry
from marlin_tpu.serving import EngineFrontend, ServingEngine, faults
from marlin_tpu.serving.engine import _decode_round_spec

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(**kw):
    base = dict(vocab=48, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                max_len=96)
    base.update(kw)
    return TransformerConfig(**base)


@pytest.fixture(autouse=True)
def _clean_plan():
    yield
    faults.reset()


def _workload(cfg):
    """Patterned prompts (drafts land) + random ones (drafts miss) +
    ragged steps — the spec round must be exact on hits AND misses."""
    rng = np.random.default_rng(13)
    prompts = [
        np.tile(np.array([5, 9, 17, 3], np.int32), 6)[:20],
        np.tile(np.array([7, 2, 11], np.int32), 8)[:18],
        rng.integers(0, cfg.vocab, 8).astype(np.int32),
        np.tile(np.array([4, 4, 9], np.int32), 10)[:24],
        rng.integers(0, cfg.vocab, 13).astype(np.int32),
    ]
    steps = [30, 25, 20, 28, 9]
    return prompts, steps


def _drain(params, cfg, spec, paged=False, order=None, **kw):
    """Run the standard workload to completion; returns (engine,
    tokens-by-workload-index, Request-by-workload-index)."""
    prompts, steps = _workload(cfg)
    eng = ServingEngine(
        params, cfg, batch=kw.pop("batch", 2),
        round_steps=kw.pop("round_steps", 4), seed=3,
        kv_pages=(cfg.max_len // 16 * 4) if paged else None,
        spec_draft_lens=(2, 4, 6) if spec else None, **kw)
    idx = list(order) if order is not None else range(len(prompts))
    for i in idx:
        eng.submit(prompts[i], steps[i], request_id=100 + i)
    eng.close()
    by_id = {r.request_id: r for r in eng.run()}
    reqs = [by_id[100 + i] for i in range(len(prompts))]
    return eng, [np.asarray(r.tokens) for r in reqs], reqs


class TestSpecExactness:
    # Plain cfg is the tier-1 representative; rope/GQA and int8-KV
    # (~15 s of compile each) run under -m slow, like test_serving.
    @pytest.mark.parametrize("cfg_kw", [
        {},
        pytest.param({"rope": True, "n_kv_heads": 1},
                     marks=pytest.mark.slow),
        pytest.param({"kv_quant": "int8"}, marks=pytest.mark.slow),
    ])
    def test_greedy_bitexact_vs_nonspec_and_generate(self, cfg_kw):
        cfg = _cfg(**cfg_kw)
        params = init_params(cfg, seed=0)
        _, base, _ = _drain(params, cfg, spec=False)
        _, spec, _ = _drain(params, cfg, spec=True)
        prompts, steps = _workload(cfg)
        for i, (a, b) in enumerate(zip(base, spec)):
            np.testing.assert_array_equal(a, b, err_msg=f"request {i}")
            ref = np.asarray(generate(
                params, jnp.asarray(prompts[i][None], jnp.int32),
                steps[i], cfg))[0]
            np.testing.assert_array_equal(b, ref, err_msg=f"request {i}")

    def test_greedy_bitexact_paged(self):
        # Paged spec vs paged non-spec vs CONTIGUOUS spec: the page-
        # granular cache and the row cache must agree to the bit under
        # speculation (same _spec_round_loop body, different KV
        # plumbing).
        cfg = _cfg()
        params = init_params(cfg, seed=0)
        _, base, _ = _drain(params, cfg, spec=False, paged=True)
        _, spec, reqs = _drain(params, cfg, spec=True, paged=True)
        _, cont, _ = _drain(params, cfg, spec=True, paged=False)
        for a, b, c in zip(base, spec, cont):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(b, c)
        assert sum(r.spec_accepted for r in reqs) > 0  # drafts landed

    def test_eos_freeze_is_exact_under_speculation(self):
        # eos inside an ACCEPTED draft must truncate the advance at
        # the eos position (the eos_cut clamp in _spec_round_loop) —
        # pin against generate(eos_id=...) and the non-spec engine.
        cfg = _cfg()
        params = init_params(cfg, seed=5)
        prompts, steps = _workload(cfg)
        free = np.asarray(generate(
            params, jnp.asarray(prompts[0][None], jnp.int32), steps[0],
            cfg))[0]
        eos = int(free[steps[0] // 2])  # mid-stream token: fires early
        _, base, _ = _drain(params, cfg, spec=False, eos_id=eos)
        _, spec, reqs = _drain(params, cfg, spec=True, eos_id=eos)
        fired = 0
        for i, (a, b) in enumerate(zip(base, spec)):
            np.testing.assert_array_equal(a, b, err_msg=f"request {i}")
            ref = np.asarray(generate(
                params, jnp.asarray(prompts[i][None], jnp.int32),
                steps[i], cfg, eos_id=eos))[0]
            np.testing.assert_array_equal(b, ref, err_msg=f"request {i}")
            fired += int((ref == eos).any())
        assert fired >= 1  # the early-stop path actually ran
        assert any(r.emitted < s for r, s in zip(reqs, steps))


class TestSpecSampledInvariance:
    def test_arrival_pattern_cannot_move_sampled_outputs(self):
        # Fixed draft length (spec_adaptive=False): per-request PRNG
        # streams make sampled output a pure function of (prompt,
        # steps, seed, request_id) — submission order and batch shape
        # must not move a byte. (The adaptive policy's draft-length
        # SEQUENCE is schedule-dependent, so adaptive sampled runs are
        # only distribution-stable, not byte-stable — which is why the
        # knob exists.)
        cfg = _cfg()
        params = init_params(cfg, seed=0)
        outs = []
        for order, batch, rsteps in ((None, 2, 4), ([2, 0, 4, 3, 1], 3, 7),
                                     ([4, 3, 2, 1, 0], 2, 16)):
            _, toks, _ = _drain(params, cfg, spec=True, order=order,
                                batch=batch, round_steps=rsteps,
                                temperature=0.8, spec_adaptive=False)
            outs.append([t.tolist() for t in toks])
        assert outs[0] == outs[1] == outs[2]


class TestSpecAccounting:
    def test_ledger_identity_and_counters(self):
        cfg = _cfg()
        params = init_params(cfg, seed=0)
        reg = MetricsRegistry()
        eng, _, reqs = _drain(params, cfg, spec=True,
                              metrics_registry=reg)
        # Every emitted token billed exactly once: the prefill's first
        # sample, a live decode iteration, or an accepted draft.
        for r in reqs:
            assert r.emitted == 1 + r.live_iters + r.spec_accepted, \
                (r.request_id, r.emitted, r.live_iters, r.spec_accepted)
            assert 0 <= r.spec_accepted <= r.spec_drafted
        st = eng.stats
        assert st.n_spec_drafted == sum(r.spec_drafted for r in reqs)
        assert st.n_spec_accepted == sum(r.spec_accepted for r in reqs)
        assert st.n_spec_accepted > 0  # patterned prompts: drafts land
        assert reg.counter("serving_spec_drafted_total").value == \
            st.n_spec_drafted
        assert reg.counter("serving_spec_accepted_total").value == \
            st.n_spec_accepted
        s = st.summary()
        assert 0.0 < s["spec_accept_lifetime"] <= 1.0
        assert s["spec_accept_rate"] == pytest.approx(
            st.spec_accept_rate(), abs=1e-4)  # summary rounds to 4dp


class TestSpecCompileBudget:
    def test_compile_set_is_the_draft_len_set(self):
        # vocab=53 makes this cfg unique to the test, so the jit-cache
        # delta is exact no matter which tests compiled what before.
        # Engine init prewarms one executable per draft length; the
        # full adaptive workload, a second engine, and every draft-
        # length switch add NOTHING.
        cfg = _cfg(vocab=53)
        params = init_params(cfg, seed=6)
        lens = (2, 4, 6)
        cache0 = _decode_round_spec._cache_size()
        eng = ServingEngine(params, cfg, batch=2, round_steps=4,
                            spec_draft_lens=lens)
        assert _decode_round_spec._cache_size() == cache0 + len(lens)
        prompts, steps = _workload(cfg)
        for p, s in zip(prompts, steps):
            eng.submit(p, s)
        eng.close()
        eng.run()
        assert _decode_round_spec._cache_size() == cache0 + len(lens)
        eng2 = ServingEngine(params, cfg, batch=2, round_steps=4,
                             spec_draft_lens=lens)
        eng2.submit(prompts[0], 6)
        eng2.run()
        assert _decode_round_spec._cache_size() == cache0 + len(lens)


class TestSpecSubmitValidation:
    def test_overhang_tightens_the_extent_check(self):
        # A live row's verify chunk may write up to draft_len_max - 1
        # slots past its own target; submit must refuse an extent that
        # fits without speculation but not with the overhang.
        cfg = _cfg()
        params = init_params(cfg, seed=0)
        plain = ServingEngine(params, cfg, batch=1)
        spec = ServingEngine(params, cfg, batch=1,
                             spec_draft_lens=(2, 8))
        prompt = np.ones(20, np.int32)
        fits_plain = cfg.max_len - 20  # exactly max_len without spec
        plain.submit(prompt, fits_plain)
        with pytest.raises(ValueError, match="overhang"):
            spec.submit(prompt, fits_plain)
        spec.submit(prompt, fits_plain - 7)  # minus overhang: fits

    def test_prompt_shorter_than_ngram_is_rejected(self):
        cfg = _cfg()
        eng = ServingEngine(init_params(cfg, seed=0), cfg, batch=1,
                            spec_draft_lens=(4,), spec_ngram=3)
        with pytest.raises(ValueError, match="spec_ngram"):
            eng.submit(np.ones(2, np.int32), steps=4)
        eng.submit(np.ones(3, np.int32), steps=4)  # boundary admits

    def test_knob_validation(self):
        cfg = _cfg()
        params = init_params(cfg, seed=0)
        with pytest.raises(ValueError, match="non-empty"):
            ServingEngine(params, cfg, spec_draft_lens=())
        with pytest.raises(ValueError, match=">= 2"):
            ServingEngine(params, cfg, spec_draft_lens=(1, 4))
        with pytest.raises(ValueError, match="spec_ngram"):
            ServingEngine(params, cfg, spec_draft_lens=(4,),
                          spec_ngram=0)
        with pytest.raises(ValueError, match="max_len"):
            ServingEngine(params, cfg, spec_draft_lens=(cfg.max_len,))


class TestSpecCrashRecovery:
    def test_crash_midstream_recovers_bitexact_with_spec_knobs(self):
        # The test_faults.py decode_round contract on the SPEC round:
        # crash round 2 under the supervised frontend, recover, and
        # every request matches an uninterrupted spec run bit-exactly.
        # Greedy on purpose: the adaptive draft-length SEQUENCE isn't
        # arrival-stable, and a restart changes arrivals — greedy
        # output is draft-length-independent, so the golden stands.
        cfg = _cfg()
        params = init_params(cfg, seed=0)
        prompts, steps = _workload(cfg)
        gold_eng = ServingEngine(params, cfg, batch=2, round_steps=4,
                                 spec_draft_lens=(2, 4, 6))
        for p, s in zip(prompts, steps):
            gold_eng.submit(p, s)
        gold = {r.request_id: list(map(int, r.tokens))
                for r in gold_eng.run()}

        plan = faults.install(faults.FaultPlan())
        plan.add(site="decode_round", round=2)
        reg = MetricsRegistry()
        eng = ServingEngine(params, cfg, batch=2, round_steps=4,
                            spec_draft_lens=(2, 4, 6),
                            metrics_registry=reg)
        fe = EngineFrontend(eng).start()
        handles = [fe.submit(p, s) for p, s in zip(prompts, steps)]
        results = {h.request_id: h.result(60.0) for h in handles}
        faults.reset()

        assert fe.restarts == 1
        # The successor engine carries the spec configuration — the
        # crash must not silently degrade the fleet to non-spec.
        assert fe.engine.spec
        assert fe.engine.spec_draft_lens == (2, 4, 6)
        for rid, r in results.items():
            assert r.status == "done"
            assert list(map(int, r.tokens)) == gold[rid], rid
            assert r.emitted == 1 + r.live_iters + r.spec_accepted
        st = fe.engine.stats
        assert st.n_completed == len(prompts)
        assert reg.counter("serving_engine_restarts_total").value == 1
        assert fe.drain(30.0)


class TestSpecSloSmoke:
    def test_bench_serving_spec_line_and_slo_gate(self, tmp_path):
        # End-to-end CI form: `bench.py --config serving_spec` on the
        # COMMITTED checkpoint at default knobs (~10 s: tiny model,
        # min-of-2 trials per arm), then the whole artifact through
        # tools/slo_check.py --metrics-key metrics_spec against the
        # committed baseline — 1.5x floor at measured acceptance,
        # TTFT unharmed, zero recompiles in both arms.
        env = dict(os.environ, BENCH_FORCE_CPU="1", BENCH_RETRIES="1")
        r = subprocess.run(
            [sys.executable, "bench.py", "--config", "serving_spec"],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=_REPO)
        assert r.returncode == 0, r.stderr[-800:]
        lines = [json.loads(l) for l in r.stdout.strip().splitlines()]
        (line,) = [d for d in lines
                   if d["metric"] == "serving_spec_decode"]
        assert line["value"] >= 1.5, line
        assert line["bit_exact_vs_nonspec"] is True
        assert line["accept_rate_lifetime"] >= 0.2
        assert line["recompiles_after_warmup"] == 0
        assert line["recompiles_after_warmup_off"] == 0
        assert line["spec_accepted"] > 0
        assert line["draft_len_final"] in line["draft_lens"]
        # Fewer rounds is the MECHANISM of the speedup — pin it so the
        # ratio can't pass on weather alone.
        assert line["rounds_on"] < line["rounds_off"]
        m = line["metrics"]
        assert m["counters"]["serving_spec_accepted_total"] > 0
        assert m["gauges"]["serving_spec_accept_rate"] > 0
        artifact = tmp_path / "spec_artifact.jsonl"
        artifact.write_text(r.stdout)
        slo = subprocess.run(
            [sys.executable, "tools/slo_check.py", str(artifact),
             "--metrics-key", "metrics_spec"],
            capture_output=True, text=True, timeout=60, cwd=_REPO)
        assert slo.returncode == 0, slo.stdout + slo.stderr
        assert "SLO OK" in slo.stdout
