"""Checkpoint/restore tests — sharded save + device-direct sharded restore."""

import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu.matrix.block import BlockMatrix
from marlin_tpu.matrix.dense import DenseVecMatrix
from marlin_tpu.utils import checkpoint as ckpt


class TestMatrixCheckpoint:
    def test_dense_roundtrip(self, tmp_path, rng):
        a = rng.standard_normal((23, 11))  # uneven: exercises padded physical
        m = DenseVecMatrix(a)
        ckpt.save_matrix(m, str(tmp_path / "m"))
        back = ckpt.load_matrix(str(tmp_path / "m"))
        assert isinstance(back, DenseVecMatrix)
        assert back.shape == (23, 11)
        np.testing.assert_allclose(back.to_numpy(), a)
        # Restored sharded, not single-device.
        assert len(back.data.sharding.device_set) == 8

    def test_block_roundtrip_with_grid(self, tmp_path, rng):
        a = rng.standard_normal((10, 14))
        m = BlockMatrix(a, blks_by_row=5, blks_by_col=7)
        ckpt.save_matrix(m, str(tmp_path / "b"))
        back = ckpt.load_matrix(str(tmp_path / "b"))
        assert isinstance(back, BlockMatrix)
        assert (back.blks_by_row, back.blks_by_col) == (5, 7)
        np.testing.assert_allclose(back.to_numpy(), a)

    def test_restored_matrix_computes(self, tmp_path, rng):
        a = rng.standard_normal((16, 16))
        ckpt.save_matrix(DenseVecMatrix(a), str(tmp_path / "m"))
        back = ckpt.load_matrix(str(tmp_path / "m"))
        c = back.multiply(back, mode="summa")
        np.testing.assert_allclose(c.to_numpy(), a @ a, rtol=1e-10)


class TestPytreeCheckpoint:
    def test_params_roundtrip(self, tmp_path):
        from marlin_tpu.examples.neural_network import init_params

        params = init_params(8, 4, 2, seed=3)
        ckpt.save_pytree(params, str(tmp_path / "params"))
        back = ckpt.load_pytree(str(tmp_path / "params"))
        for k in params:
            np.testing.assert_allclose(np.asarray(back[k]), np.asarray(params[k]))
