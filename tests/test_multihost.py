"""Multi-host (DCN) evidence: a REAL 2-process ``jax.distributed`` run.

The reference's multi-node story is Spark's driver/executor backend; the
framework's is ``mesh.init_distributed`` (SURVEY.md §2.8 DCN mapping). The
8-device single-process mesh used everywhere else exercises collectives but
not the process boundary — this test launches two actual OS processes, each
with 4 virtual CPU devices, that rendezvous through the JAX coordination
service and build one spanning 8-device mesh. See ``multihost_worker.py`` for
what runs on it (cross-process psum, SUMMA, sharded-type GEMM, checkpoint
save/restore).
"""

import os
import socket
import subprocess
import sys

import pytest

import jax

_JAX_VERSION = tuple(int(x) for x in jax.__version__.split(".")[:3])
if _JAX_VERSION < (0, 5, 0):
    # ROADMAP item 11: this image pins jax 0.4.37, whose CPU backend
    # rejects cross-process computations outright — every worker dies in
    # rendezvous with "Multiprocess computations aren't implemented on
    # the CPU backend" (XLA CPU collectives across processes landed in
    # the 0.5.x line). Skip at module level so the suite reports the
    # version gap instead of burning two 540 s worker launches on a
    # known-impossible pass.
    pytest.skip(
        "jax 0.4.37 CPU backend: 'Multiprocess computations aren't "
        "implemented on the CPU backend' — the 2-/4-process spanning "
        "mesh needs jax >= 0.5", allow_module_level=True)

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("nproc", [2, 4])
def test_spanning_mesh_processes(tmp_path, nproc):
    # 2 processes catch the boundary itself; 4 catch rank-indexing bugs a
    # symmetric 2-way split can hide (VERDICT r02 item 7). Both build the
    # same 8-device global mesh (8 // nproc local devices each) and run
    # psum/SUMMA/dispatch GEMM/checkpoint plus dist LU, an ALS half-step,
    # and a transformer dp train step across the process boundary.
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")

    def launch():
        port = _free_port()
        procs = [
            subprocess.Popen(
                [sys.executable, _WORKER, str(i), str(nproc), str(port),
                 str(tmp_path)],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                env=env,
            )
            for i in range(nproc)
        ]
        outs = []
        try:
            for p in procs:
                out, err = p.communicate(timeout=540)
                outs.append((p.returncode, out, err))
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            return None
        return outs

    # The N-process coordination-service rendezvous is timing-sensitive
    # under host load (observed: a one-off worker failure in a full-suite
    # run that passes in isolation) — retry the whole launch once, but ONLY
    # for timeout/rendezvous-shaped failures (ADVICE r04: a blanket retry
    # masks real intermittent cross-process bugs), and print the first
    # attempt's output first so a passing retry still leaves a flake trace.
    # Marks are the PRECISE gRPC/coordination-service status tokens, not
    # generic English ("barrier"/"coordination"/"heartbeat" would also
    # match a real cross-process assertion failure whose message mentions
    # the primitive, silently retrying a genuine bug — advisor r05 low #3).
    # Status codes match CASE-SENSITIVELY (always emitted uppercase;
    # folding would let prose like "device unavailable" back in); the two
    # connect-phase phrases fold, since they appear as "Connection
    # refused" (errno) and "Failed to connect" (gRPC) in the wild.
    _STATUS_MARKS = ("DEADLINE_EXCEEDED", "UNAVAILABLE")
    _CONNECT_MARKS = ("failed to connect", "connection refused")

    def _transient(outs) -> bool:
        if outs is None:
            return True  # whole-launch timeout

        def rendezvous_shaped(text: str) -> bool:
            return any(m in text for m in _STATUS_MARKS) \
                or any(m in text.lower() for m in _CONNECT_MARKS)

        return any(rc != 0 and rendezvous_shaped(out + err)
                   for rc, out, err in outs)

    outs = launch()
    if outs is not None and all(rc == 0 for rc, _, _ in outs):
        pass  # first attempt clean
    elif _transient(outs):
        if outs is None:
            print("multihost attempt 1 timed out; retrying", flush=True)
        else:
            for i, (rc, out, err) in enumerate(outs):
                if rc != 0:
                    print(f"multihost attempt 1 worker {i} rc={rc} "
                          f"(rendezvous-shaped, retrying)\nstdout:\n{out}\n"
                          f"stderr:\n{err[-3000:]}", flush=True)
        outs = launch()
    # Non-transient first-attempt failures fall through to the assertions
    # below and fail loudly with their own output.
    if outs is None:
        pytest.fail("multihost workers timed out (both attempts)")
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"worker {i} rc={rc}\nstdout:\n{out}\nstderr:\n{err[-3000:]}"
        assert f"MULTIHOST_OK pid={i}" in out, (out, err[-2000:])
