"""Text I/O tests — the reference's exact formats, plus roundtrips and the
shipped sample-data files."""

import os

import numpy as np
import pytest

from marlin_tpu.matrix.block import BlockMatrix
from marlin_tpu.matrix.dense import DenseVecMatrix
from marlin_tpu.utils import io as mio


class TestDenseFormat:
    def test_roundtrip(self, tmp_path, rng):
        a = rng.standard_normal((9, 5))
        path = str(tmp_path / "m")
        DenseVecMatrix(a).save_to_file_system(path)
        assert os.path.exists(os.path.join(path, "_SUCCESS"))
        back = mio.load_dense_matrix(path)
        np.testing.assert_allclose(back.to_numpy(), a)

    def test_description(self, tmp_path, rng):
        a = rng.standard_normal((4, 6))
        path = str(tmp_path / "m")
        DenseVecMatrix(a).save_with_description(path, name="testmat")
        name, rows, cols = mio.load_description(path)
        assert (name, rows, cols) == ("testmat", 4, 6)

    def test_parse_variants(self, tmp_path):
        # Loader accepts comma or whitespace separators (MTUtils.scala regex).
        p = tmp_path / "f.txt"
        p.write_text("0:1.0,2.0,3.0\n2:7.0 8.0 9.0\n1:4.0, 5.0, 6.0\n")
        m = mio.load_dense_matrix(str(p))
        np.testing.assert_allclose(
            m.to_numpy(), [[1, 2, 3], [4, 5, 6], [7, 8, 9]]
        )

    def test_shipped_sample_data_format(self, tmp_path):
        # The reference ships data/a.100.100 in this format; emulate a slice.
        p = tmp_path / "a.3.3"
        p.write_text("0:1,0,2\n1:0,1,0\n2:2,0,1\n")
        m = mio.load_dense_matrix(str(p))
        assert m.shape == (3, 3)


class TestBlockFormat:
    def test_roundtrip_uneven_grid(self, tmp_path, rng):
        a = rng.standard_normal((5, 7))
        path = str(tmp_path / "b")
        BlockMatrix(a, blks_by_row=2, blks_by_col=3).save_to_file_system(path)
        back = mio.load_block_matrix(path)
        np.testing.assert_allclose(back.to_numpy(), a)
        assert (back.blks_by_row, back.blks_by_col) == (2, 3)

    def test_column_major_data(self, tmp_path):
        # `r-c-rows-cols:data` carries column-major data (Breeze BDM.create).
        p = tmp_path / "blk.txt"
        p.write_text("0-0-2-2:1.0,3.0,2.0,4.0\n")
        m = mio.load_block_matrix(str(p))
        np.testing.assert_allclose(m.to_numpy(), [[1.0, 2.0], [3.0, 4.0]])


class TestCoordinateFormat:
    def test_load_with_timestamp(self, tmp_path):
        # MovieLens-tolerant: 4th field ignored (MTUtils.scala:239-241).
        p = tmp_path / "r.txt"
        p.write_text("0,0,5.0,838985046\n1,2,3.0\n2 1 4.0\n")
        cm = mio.load_coordinate_matrix(str(p))
        assert cm.shape == (3, 3) and cm.nnz == 3
        dense = cm.to_numpy()
        assert dense[0, 0] == 5.0 and dense[1, 2] == 3.0 and dense[2, 1] == 4.0

    def test_to_dense_vec_matrix(self, tmp_path):
        p = tmp_path / "r.txt"
        p.write_text("0,1,2.0\n1,0,3.0\n")
        dvm = mio.load_coordinate_matrix(str(p)).to_dense_vec_matrix()
        np.testing.assert_allclose(dvm.to_numpy(), [[0, 2], [3, 0]])


class TestSVMFormat:
    def test_one_based_indices(self, tmp_path):
        p = tmp_path / "svm.txt"
        p.write_text("0 1:1.5 3:2.5\n1 2:4.0\n")
        m = mio.load_svm_den_vec_matrix(str(p), vector_len=4)
        np.testing.assert_allclose(
            m.to_numpy(), [[1.5, 0, 2.5, 0], [0, 4.0, 0, 0]]
        )


class TestArrayHelpers:
    def test_array_matrix_roundtrip(self, rng):
        a = rng.standard_normal((6, 4))
        m = mio.array_to_matrix(a)
        assert isinstance(m, DenseVecMatrix)
        np.testing.assert_allclose(mio.matrix_to_array(m), a)


class TestStreamingLoader:
    def test_matches_buffered_loader(self, tmp_path, rng):
        a = rng.standard_normal((23, 7))
        m = DenseVecMatrix(a)
        path = str(tmp_path / "mat")
        mio.save_dense_matrix(m, path)
        buffered = mio.load_dense_matrix(path, streaming=False)
        streamed = mio.load_dense_matrix_streaming(path)
        np.testing.assert_allclose(streamed.to_numpy(), buffered.to_numpy())
        np.testing.assert_allclose(streamed.to_numpy(), a)
        assert streamed.shape == (23, 7)

    def test_streaming_flag_forces_path(self, tmp_path, rng):
        a = rng.standard_normal((9, 3))
        path = str(tmp_path / "mat")
        mio.save_dense_matrix(DenseVecMatrix(a), path)
        m = mio.load_dense_matrix(path, streaming=True)
        np.testing.assert_allclose(m.to_numpy(), a)

    def test_result_is_sharded_over_all_devices(self, tmp_path, rng, mesh):
        a = rng.standard_normal((33, 5))
        path = str(tmp_path / "mat")
        mio.save_dense_matrix(DenseVecMatrix(a), path)
        m = mio.load_dense_matrix_streaming(path)
        assert len(m.data.sharding.device_set) == len(mesh.devices.flat)
        # The streamed result feeds compute directly.
        out = m.multiply(m.to_numpy().T)
        np.testing.assert_allclose(out.to_numpy(), a @ a.T, rtol=1e-10)

    def test_out_of_order_and_gappy_rows(self, tmp_path):
        p = tmp_path / "scattered.txt"
        # Rows out of order, row 1 missing entirely (stays zero).
        p.write_text("3:1.0,2.0\n0:5.0,6.0\n2:7.0,8.0\n")
        m = mio.load_dense_matrix_streaming(str(p))
        np.testing.assert_allclose(
            m.to_numpy(), [[5, 6], [0, 0], [7, 8], [1, 2]]
        )

    def test_explicit_shape_skips_prepass(self, tmp_path):
        p = tmp_path / "m.txt"
        p.write_text("0:1.0,2.0\n1:3.0,4.0\n")
        m = mio.load_dense_matrix_streaming(str(p), shape=(4, 2))
        np.testing.assert_allclose(m.to_numpy(), [[1, 2], [3, 4], [0, 0], [0, 0]])


class TestFromRowStream:
    def test_from_rows_routes_through_stream(self, rng):
        vecs = [(i, rng.standard_normal(4)) for i in range(11)]
        m = DenseVecMatrix.from_rows(vecs)
        expect = np.stack([v for _, v in vecs])
        np.testing.assert_allclose(m.to_numpy(), expect)

    def test_duplicate_row_after_ship_raises(self, mesh):
        # In-order stream ships each stripe when complete; a duplicate row
        # arriving later must fail loudly, not silently overwrite.
        n_dev = len(mesh.devices.flat)
        rows = [(i, np.ones(2)) for i in range(n_dev * 2)] + [(0, np.zeros(2))]
        with pytest.raises(ValueError, match="shipped"):
            DenseVecMatrix.from_row_stream(iter(rows), (n_dev * 2, 2))

    def test_stream_larger_than_stripe_ships_incrementally(self, mesh):
        # Ordered stream: once a stripe's rows all arrive it must leave the
        # host buffer dict (the bounded-memory property).
        n_dev = len(mesh.devices.flat)
        m = DenseVecMatrix.from_row_stream(
            ((i, np.full(3, i)) for i in range(n_dev * 4)), (n_dev * 4, 3)
        )
        expect = np.repeat(np.arange(n_dev * 4)[:, None], 3, 1)
        np.testing.assert_allclose(m.to_numpy(), expect)


class TestChunkedStreaming:
    def test_python_fallback_matches_native(self, tmp_path, rng, monkeypatch):
        from marlin_tpu import native as native_mod

        a = rng.standard_normal((19, 6))
        path = str(tmp_path / "m")
        mio.save_dense_matrix(DenseVecMatrix(a), path)
        via_native = mio.load_dense_matrix_streaming(path).to_numpy()
        monkeypatch.setattr(native_mod, "available", lambda: False)
        via_python = mio.load_dense_matrix_streaming(path).to_numpy()
        np.testing.assert_allclose(via_native, via_python)
        np.testing.assert_allclose(via_python, a)

    def test_chunk_boundary_mid_file(self, tmp_path, rng, monkeypatch):
        # Force tiny chunks so lines split across read boundaries.
        a = rng.standard_normal((37, 4))
        path = str(tmp_path / "m")
        mio.save_dense_matrix(DenseVecMatrix(a), path)
        monkeypatch.setattr(mio, "STREAM_CHUNK_BYTES", 64)
        m = mio.load_dense_matrix_streaming(path)
        np.testing.assert_allclose(m.to_numpy(), a)

    def test_from_row_chunks_direct(self, rng):
        idx = np.array([2, 0, 5, 1, 3, 4])
        vals = rng.standard_normal((6, 3))
        m = DenseVecMatrix.from_row_chunks(
            [(idx[:3], vals[:3]), (idx[3:], vals[3:])], (6, 3)
        )
        expect = np.zeros((6, 3))
        expect[idx] = vals
        np.testing.assert_allclose(m.to_numpy(), expect)


def test_streaming_honors_use_native_false(tmp_path, rng, monkeypatch):
    # use_native=False must bypass the codec on the auto-streaming route too.
    from marlin_tpu import native as native_mod

    a = rng.standard_normal((9, 3))
    path = str(tmp_path / "m")
    mio.save_dense_matrix(DenseVecMatrix(a), path)

    def boom(*args, **kwargs):
        raise AssertionError("native codec used despite use_native=False")

    monkeypatch.setattr(native_mod, "parse_dense_chunk", boom)
    monkeypatch.setattr(native_mod, "probe_dense_text", boom)
    m = mio.load_dense_matrix(path, use_native=False, streaming=True)
    np.testing.assert_allclose(m.to_numpy(), a)


class TestRemoteFilesystem:
    """Every loader/saver must accept fsspec URIs — the analogue of the
    reference reading/writing any Hadoop FS URI (MTUtils.scala:286/324).
    fsspec's memory:// filesystem stands in for gs:// in CI."""

    @pytest.fixture
    def memfs_root(self):
        import uuid

        import fsspec

        root = f"memory://io_test_{uuid.uuid4().hex[:8]}"
        yield root
        fs, p = fsspec.core.url_to_fs(root)
        if fs.exists(p):
            fs.rm(p, recursive=True)

    def test_dense_roundtrip(self, memfs_root, rng):
        a = rng.standard_normal((9, 5))
        path = memfs_root + "/m"
        mio.save_dense_matrix(DenseVecMatrix(a), path)
        back = mio.load_dense_matrix(path)
        np.testing.assert_allclose(back.to_numpy(), a)

    def test_dense_streaming_roundtrip(self, memfs_root, rng):
        a = rng.standard_normal((23, 7))
        path = memfs_root + "/ms"
        mio.save_dense_matrix(DenseVecMatrix(a), path, parts=3)
        m = mio.load_dense_matrix_streaming(path)
        np.testing.assert_allclose(m.to_numpy(), a)

    def test_block_roundtrip(self, memfs_root, rng):
        a = rng.standard_normal((5, 7))
        path = memfs_root + "/b"
        BlockMatrix(a, blks_by_row=2, blks_by_col=3).save_to_file_system(path)
        back = mio.load_block_matrix(path)
        np.testing.assert_allclose(back.to_numpy(), a)
        assert (back.blks_by_row, back.blks_by_col) == (2, 3)

    def test_coordinate_load(self, memfs_root):
        import fsspec

        path = memfs_root + "/coo.txt"
        with fsspec.open(path, "w") as f:
            f.write("0,0,5.0\n1,2,3.0\n")
        cm = mio.load_coordinate_matrix(path)
        assert cm.shape == (2, 3) and cm.nnz == 2

    def test_svm_load(self, memfs_root):
        import fsspec

        path = memfs_root + "/svm.txt"
        with fsspec.open(path, "w") as f:
            f.write("0 1:1.5 3:2.5\n1 2:4.0\n")
        m = mio.load_svm_den_vec_matrix(path, vector_len=4)
        np.testing.assert_allclose(
            m.to_numpy(), [[1.5, 0, 2.5, 0], [0, 4.0, 0, 0]]
        )

    def test_description_roundtrip(self, memfs_root, rng):
        a = rng.standard_normal((4, 6))
        path = memfs_root + "/d"
        DenseVecMatrix(a).save_with_description(path, name="remote")
        assert mio.load_description(path) == ("remote", 4, 6)

    def test_hidden_part_files_skipped(self, memfs_root):
        import fsspec

        path = memfs_root + "/dir"
        with fsspec.open(path + "/part-00000", "w") as f:
            f.write("0:1.0,2.0\n")
        with fsspec.open(path + "/_SUCCESS", "w") as f:
            f.write("")
        m = mio.load_dense_matrix(path)
        np.testing.assert_allclose(m.to_numpy(), [[1.0, 2.0]])


class TestLoaderEdgeCases:
    def test_no_trailing_newline(self, tmp_path):
        p = tmp_path / "m.txt"
        p.write_bytes(b"0:1.0,2.0\n1:3.0,4.0")  # no final \n
        m = mio.load_dense_matrix_streaming(str(p))
        np.testing.assert_allclose(m.to_numpy(), [[1, 2], [3, 4]])

    def test_multifile_dir_boundaries(self, tmp_path, rng):
        # Rows split across part files; a line must never straddle files.
        d = tmp_path / "dir"
        d.mkdir()
        (d / "part-00000").write_text("0:1.0\n1:2.0\n")
        (d / "part-00001").write_text("2:3.0")
        (d / "_SUCCESS").write_text("")
        m = mio.load_dense_matrix_streaming(str(d))
        np.testing.assert_allclose(m.to_numpy(), [[1.0], [2.0], [3.0]])

    def test_streaming_empty_input_raises(self, tmp_path):
        p = tmp_path / "empty.txt"
        p.write_text("")
        with pytest.raises(ValueError, match="no matrix rows"):
            mio.load_dense_matrix_streaming(str(p))
