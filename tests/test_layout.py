"""Placement-helper tests (partitioner-parity formulas)."""

import jax
import pytest

import marlin_tpu as mt
from marlin_tpu.parallel.layout import (
    BlockID,
    colocated,
    device_for_block,
    device_for_row,
    elem_op_partition,
    grid_seq,
)


class TestPartitionFormulas:
    def test_grid_seq_covers_all_cells(self):
        m, k, n = 2, 3, 2
        seqs = {
            grid_seq(BlockID(i, j), m, k, n, kk)
            for i in range(m)
            for j in range(n)
            for kk in range(k)
        }
        assert seqs == set(range(m * k * n))  # numPartitions = m*k*n

    def test_elem_op_partition(self):
        assert elem_op_partition(BlockID(2, 1), blks_by_col=4) == 9


class TestDeviceOwnership:
    def test_block_owner_in_mesh(self):
        mesh = mt.default_mesh()
        devs = set(mesh.devices.flat)
        owners = {
            device_for_block(bi, bj, 4, 4, mesh) for bi in range(4) for bj in range(4)
        }
        assert owners <= devs and len(owners) == 8  # 4x4 grid over 4x2 mesh

    def test_row_striping(self):
        mesh = mt.default_mesh()
        devs = list(mesh.devices.flat)
        assert device_for_row(0, 80, mesh) == devs[0]
        assert device_for_row(79, 80, mesh) == devs[-1]

    def test_colocation_matches_striping(self):
        mesh = mt.default_mesh()
        # Row stripe i and chunk i of an equally-chunked vector share a device.
        assert colocated(0, 0, 64, 8, mesh)
        assert colocated(63, 7, 64, 8, mesh)
        assert not colocated(0, 7, 64, 8, mesh)
