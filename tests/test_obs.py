"""Observability subsystem tests (marlin_tpu/obs/).

The package's acceptance claims, each pinned mechanically:

* TRACE — spans nest (parent/depth recorded, time containment), the
  export is valid Chrome/Perfetto ``trace_event`` JSON (``json.load``
  round-trip, well-formed ``ph``/``ts``/``dur`` fields), and a DISABLED
  tracer records nothing.
* METRICS — labeled series, exact histogram bucket counts, and the
  Prometheus text exposition (cumulative ``le`` buckets, ``_sum``/
  ``_count``, sanitized names).
* WATCHDOG — an INDUCED retrace on a registered jitted entry point is
  caught (poll + the scoped ``no_recompiles`` assertion), and the
  ``jax.monitoring`` listener sees backend compiles where this jax
  exposes the hook.
* RUNLOG — bounded under a long run (retained events capped, lifetime
  count exact), JSONL round-trips.
* SERVING — an instrumented engine emits per-round and per-request
  events, feeds the TTFT / per-token-latency histograms, logs ZERO
  compile events in steady state, and the instrumented round stays
  within 5% of the no-op (disabled-tracer) path.
"""

import json
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from marlin_tpu.models import TransformerConfig, init_params
from marlin_tpu.obs import metrics as om
from marlin_tpu.obs import trace as otr
from marlin_tpu.obs.runlog import RunLog
from marlin_tpu.obs.trace import Tracer
from marlin_tpu.obs.watch import (CompileWatchdog, RetraceError,
                                  no_transfers)
from marlin_tpu.serving import ServingEngine


@pytest.fixture(autouse=True)
def _fresh_obs():
    om.registry.reset()
    otr.tracer.disable()
    otr.tracer.reset()
    yield
    om.registry.reset()
    otr.tracer.disable()
    otr.tracer.reset()


class TestTracer:
    def test_span_nesting_and_chrome_trace_roundtrip(self, tmp_path):
        tr = Tracer(enabled=True)
        with tr.span("outer", phase="x"):
            with tr.span("inner"):
                time.sleep(0.001)
            with tr.span("inner2"):
                pass
        path = tr.export(tmp_path / "trace.json")
        with open(path) as f:
            doc = json.load(f)  # the round-trip IS the format check
        evs = doc["traceEvents"]
        assert len(evs) == 3
        by = {e["name"]: e for e in evs}
        for e in evs:
            assert e["ph"] == "X"
            assert isinstance(e["ts"], float) and e["ts"] >= 0
            assert isinstance(e["dur"], float) and e["dur"] >= 0
            assert isinstance(e["tid"], int)
        # Nesting: both inners record outer as parent at depth 1, and sit
        # inside outer's [ts, ts + dur] interval.
        out = by["outer"]
        assert out["args"]["depth"] == 0 and out["args"]["phase"] == "x"
        for name in ("inner", "inner2"):
            e = by[name]
            assert e["args"]["parent"] == "outer"
            assert e["args"]["depth"] == 1
            assert e["ts"] >= out["ts"]
            assert e["ts"] + e["dur"] <= out["ts"] + out["dur"] + 1e-6

    def test_disabled_tracer_records_nothing(self):
        tr = Tracer(enabled=False)

        @tr.trace
        def f():
            return 41

        assert f() == 41
        with tr.span("nope"):
            pass
        assert tr.events() == []
        tr.enable()
        assert f() == 41
        (ev,) = tr.events()
        assert ev["name"].endswith("f")

    def test_bounded_events(self):
        tr = Tracer(enabled=True, max_events=8)
        for i in range(30):
            with tr.span(f"s{i}"):
                pass
        evs = tr.events()
        assert len(evs) == 8
        assert evs[-1]["name"] == "s29"  # newest retained

    def test_sample_rate_keeps_1_in_n_roots_coherently(self):
        # Sampled tracing for high-QPS serving: sample_rate=0.25 keeps
        # exactly every 4th ROOT span, deterministically, and children
        # inherit the root's decision — retention is coherent (every
        # recorded child's parent is recorded; dropped traces vanish
        # whole), so parent links never dangle in the export.
        tr = Tracer(enabled=True, sample_rate=0.25)
        for i in range(8):
            with tr.span(f"root-{i}"):
                with tr.span(f"child-{i}"):
                    with tr.span(f"grand-{i}"):
                        pass
        evs = tr.events()
        assert len(evs) == 6  # 2 of 8 traces kept, 3 spans each
        kept = {e["name"] for e in evs}
        assert kept == {"root-3", "child-3", "grand-3",
                        "root-7", "child-7", "grand-7"}
        for e in evs:
            parent = e["args"].get("parent")
            assert parent is None or parent in kept
        # reset() restarts the deterministic counter: replayable tests.
        tr.reset()
        with tr.span("again-0"):
            pass
        assert tr.events() == []

    def test_sample_rate_one_keeps_everything(self):
        tr = Tracer(enabled=True, sample_rate=1.0)
        for i in range(5):
            with tr.span(f"s{i}"):
                pass
        assert len(tr.events()) == 5

    def test_sample_rate_validation(self):
        with pytest.raises(ValueError, match="sample_rate"):
            Tracer(sample_rate=0.0)
        with pytest.raises(ValueError, match="sample_rate"):
            Tracer(sample_rate=1.5)

    def test_exemplar_reservoir_keeps_slowest_k(self):
        # Tail exemplars (docs/observability.md §7): request-attributed
        # spans stage per request; finish_request ranks the request by
        # its end-to-end latency and keeps the SLOWEST k complete span
        # lists — cheap for everyone else, fully explained outliers.
        tr = Tracer(enabled=True, exemplar_k=2)
        for i in range(6):
            with tr.span(f"serving.submit", scope=False, request_id=i):
                pass
            extra = [tr.span_from_stamps("serving.phase.total", 0.0,
                                         i * 1e-3, request_id=i)]
            tr.finish_request(i, total_s=i * 1e-3, extra_spans=extra)
        exs = tr.exemplars()
        assert [e["request_id"] for e in exs] == ["5", "4"]  # slowest 2
        assert exs[0]["total_s"] == pytest.approx(5e-3)
        for e in exs:
            names = {s["name"] for s in e["spans"]}
            assert names == {"serving.submit", "serving.phase.total"}
            # ... and staged spans carry the id that keyed them.
            for s in e["spans"]:
                assert str(s["args"]["request_id"]) == e["request_id"]
        doc = tr.exemplar_trace()
        assert len(doc["traceEvents"]) == 4  # 2 exemplars x 2 spans

    def test_exemplars_survive_sampling_drop(self):
        # "Sampled requests stay cheap, outliers stay fully explained":
        # exemplar staging bypasses the root-sampling draw, so a trace
        # the sampler dropped whole can still be retained as an
        # exemplar — while the main event buffer stays sampled.
        tr = Tracer(enabled=True, sample_rate=0.25, exemplar_k=8)
        for i in range(8):
            with tr.span("root", request_id=i):
                pass
            tr.finish_request(i, total_s=1.0 + i)
        assert len(tr.events()) == 2  # sampling still governs the buffer
        assert len(tr.exemplars()) == 8  # every request fully staged
        assert all(len(e["spans"]) == 1 for e in tr.exemplars())

    def test_exemplar_disabled_and_reset(self):
        tr = Tracer(enabled=True)  # exemplar_k=0: reservoir off
        with tr.span("s", request_id=1):
            pass
        assert tr.finish_request(1, 9.9) is False
        assert tr.exemplars() == []
        tr2 = Tracer(enabled=True, exemplar_k=2)
        with tr2.span("s", request_id=1):
            pass
        tr2.finish_request(1, 1.0)
        tr2.reset()
        assert tr2.exemplars() == []
        with pytest.raises(ValueError, match="exemplar_k"):
            Tracer(exemplar_k=-1)

    def test_thread_safety_and_per_thread_nesting(self):
        tr = Tracer(enabled=True)

        def work(tag):
            for _ in range(50):
                with tr.span(f"outer-{tag}"):
                    with tr.span(f"inner-{tag}"):
                        pass

        ts = [threading.Thread(target=work, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        evs = tr.events()
        assert len(evs) == 4 * 50 * 2
        # Parent tracking is per-thread: every inner-i names outer-i,
        # never another thread's span.
        for e in evs:
            if e["name"].startswith("inner-"):
                tag = e["name"].split("-")[1]
                assert e["args"]["parent"] == f"outer-{tag}"


class TestMetrics:
    def test_labeled_counters_and_gauges(self):
        reg = om.MetricsRegistry()
        reg.counter("req_total", route="a").inc()
        reg.counter("req_total", route="a").inc(2)
        reg.counter("req_total", route="b").inc()
        reg.gauge("depth").set(3)
        snap = reg.snapshot()
        assert snap["counters"]['req_total{route="a"}'] == 3
        assert snap["counters"]['req_total{route="b"}'] == 1
        assert snap["gauges"]["depth"] == 3
        json.dumps(snap)  # snapshot is JSON-able by contract

    def test_histogram_bucket_counts(self):
        reg = om.MetricsRegistry()
        h = reg.histogram("lat", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.1, 0.5, 5.0, 50.0):
            h.observe(v)
        s = reg.snapshot()["histograms"]["lat"]
        assert s["count"] == 5
        assert s["sum"] == pytest.approx(55.65)
        assert (s["min"], s["max"]) == (0.05, 50.0)
        # observe(0.1) lands IN the le=0.1 bucket (upper bounds are
        # inclusive, the Prometheus convention).
        assert s["buckets"] == {"0.1": 2, "1.0": 1, "10.0": 1, "+Inf": 1}

    def test_kind_conflict_raises(self):
        reg = om.MetricsRegistry()
        reg.counter("x").inc()
        with pytest.raises(ValueError, match="counter"):
            reg.gauge("x")

    def test_counter_rejects_negative(self):
        reg = om.MetricsRegistry()
        with pytest.raises(ValueError, match="up"):
            reg.counter("c").inc(-1)

    def test_prometheus_exposition(self):
        reg = om.MetricsRegistry()
        reg.counter("req.total", route="a").inc(3)
        reg.gauge("depth").set(2)
        h = reg.histogram("lat", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        text = reg.prometheus()
        lines = text.splitlines()
        # Name sanitized to the Prometheus charset; TYPE headers present.
        assert "# TYPE req_total counter" in lines
        assert 'req_total{route="a"} 3' in lines
        assert "# TYPE depth gauge" in lines
        assert "depth 2" in lines
        assert "# TYPE lat histogram" in lines
        # Exposition buckets are CUMULATIVE; +Inf equals _count.
        assert 'lat_bucket{le="0.1"} 1' in lines
        assert 'lat_bucket{le="1"} 2' in lines
        assert 'lat_bucket{le="+Inf"} 3' in lines
        assert "lat_sum 5.55" in lines
        assert "lat_count 3" in lines

    def test_help_lines_in_exposition(self):
        # The exposition-format satellite: families constructed with
        # help= get a `# HELP` line immediately before their `# TYPE`
        # line, with format escaping; helpless families emit TYPE only.
        reg = om.MetricsRegistry()
        reg.counter("req_total", help="requests\nover two lines",
                    route="a").inc()
        reg.gauge("depth").set(1)  # no help: no HELP line
        reg.histogram("lat", help="latency s").observe(0.2)
        reg.counter("req_total").inc()  # later helpless call keeps it
        lines = reg.prometheus().splitlines()
        i = lines.index("# HELP req_total requests\\nover two lines")
        assert lines[i + 1] == "# TYPE req_total counter"
        assert "# HELP lat latency s" in lines
        assert "# TYPE depth gauge" in lines
        assert not any(l.startswith("# HELP depth") for l in lines)
        # First non-empty help wins; a later offer does not overwrite.
        reg.counter("req_total", help="other text")
        assert "# HELP req_total requests\\nover two lines" \
            in reg.prometheus().splitlines()

    def test_histogram_bucket_exemplars(self):
        # Exemplars: one request id per bucket, last writer wins — the
        # breadcrumb from a slow TTFT bucket to its retained trace. They
        # travel in the JSON snapshot; the text exposition stays plain.
        reg = om.MetricsRegistry()
        h = reg.histogram("ttft", buckets=(0.1, 1.0))
        h.observe(0.05, exemplar="7")
        h.observe(0.06, exemplar="9")
        h.observe(5.0, exemplar="13")
        h.observe(0.5)  # no exemplar offered: bucket stays unattributed
        s = reg.snapshot()["histograms"]["ttft"]
        assert s["exemplars"] == {"0.1": "9", "+Inf": "13"}
        assert "exemplar" not in reg.prometheus()
        h2 = reg.histogram("plain", buckets=(1.0,))
        h2.observe(0.5)
        assert "exemplars" not in reg.snapshot()["histograms"]["plain"]

    def test_one_snapshot_covers_timing_shim_and_engine_series(self):
        # The dedup satellite: utils/timing writes into the SAME default
        # registry the serving engine publishes to — one snapshot, both
        # surfaces.
        from marlin_tpu.utils import timing

        with timing.timed("op.block"):
            pass
        om.registry.gauge("serving_occupancy").set(4)
        snap = om.registry.snapshot()
        assert "op.block" in snap["histograms"]
        assert snap["counters"]["op.block.calls"] == 1
        assert snap["gauges"]["serving_occupancy"] == 4
        timing.metrics.reset()


class TestWatchdog:
    def test_poll_and_scoped_check_catch_induced_retrace(self):
        f = jax.jit(lambda x: x * 2.0)
        f(jnp.ones((3,), jnp.float32))  # first compile, pre-baseline
        wd = CompileWatchdog()
        wd.register("f", f)
        f(jnp.ones((3,), jnp.float32))  # same shape: cache hit
        assert wd.poll() == []
        f(jnp.ones((2, 2), jnp.float32))  # new shape: INDUCED retrace
        (rec,) = wd.poll(rebaseline=True)
        assert rec.name == "f" and rec.new_compiles == 1
        snap = om.registry.snapshot()
        assert snap["counters"]['obs_recompiles_total{entry="f"}'] == 1
        # Scoped form: the same induction raises, naming the entry.
        with pytest.raises(RetraceError, match=r"f \(\+1\)"):
            with wd.no_recompiles():
                f(jnp.ones((4, 4), jnp.float32))
        # ... and rebaselined on exit: a clean block passes.
        with wd.no_recompiles():
            f(jnp.ones((4, 4), jnp.float32))
        assert wd.ledger().ok

    def test_register_rejects_unjitted(self):
        wd = CompileWatchdog()
        with pytest.raises(ValueError, match="_cache_size"):
            wd.register("plain", lambda x: x)

    def test_monitoring_listener_sees_backend_compile(self):
        wd = CompileWatchdog()
        if not wd.install_monitoring():
            pytest.skip("this jax has no jax.monitoring listener hook")
        try:
            before = len(wd.ledger().backend_compile_events)
            jax.jit(lambda x: x + 17.0)(jnp.ones((5,), jnp.float32))
            ledger = wd.ledger()
            assert len(ledger.backend_compile_events) > before
            assert ledger.backend_compile_seconds > 0
            assert om.registry.snapshot()["counters"][
                "obs_backend_compiles_total"] >= 1
            assert "backend compiles" in ledger.report()
        finally:
            wd.uninstall_monitoring()

    def test_no_transfers_scopes_the_guard(self):
        # CPU-backend copies are zero-copy exempt (tests/test_doctor.py),
        # so pin the plumbing: the level holds inside, restores outside.
        before = jax.config.jax_transfer_guard
        with no_transfers():
            assert jax.config.jax_transfer_guard == "disallow"
        assert jax.config.jax_transfer_guard == before


class TestRunLog:
    def test_bounded_under_long_run(self):
        log = RunLog(maxlen=16)
        for i in range(500):
            log.emit("round", round=i)
        assert len(log) == 16
        assert log.n_emitted == 500  # lifetime count stays exact
        rounds = [e["round"] for e in log.events("round")]
        assert rounds == list(range(484, 500))  # newest retained

    def test_kind_filter_and_jsonl_roundtrip(self, tmp_path):
        log = RunLog(maxlen=8)
        log.emit("round", round=0, occupied=2)
        log.emit("complete", request_id=7)
        assert [e["kind"] for e in log.events("complete")] == ["complete"]
        path = log.dump(tmp_path / "run.jsonl")
        with open(path) as f:
            lines = [json.loads(line) for line in f]
        assert len(lines) == 2
        assert lines[0]["kind"] == "round" and lines[0]["occupied"] == 2
        assert "t" in lines[0]


def _cfg(**kw):
    base = dict(vocab=48, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                max_len=96)
    base.update(kw)
    return TransformerConfig(**base)


def _submit_all(eng, workload):
    for prompt, steps in workload:
        eng.submit(prompt, steps)


def _workload(cfg, n=8, seed=13):
    rng = np.random.default_rng(seed)
    return [(rng.integers(0, cfg.vocab, int(s)), int(st))
            for s, st in zip(rng.integers(4, 14, n),
                             rng.integers(2, 18, n))]


class TestServingObservability:
    def test_engine_feeds_runlog_histograms_and_trace(self):
        cfg = _cfg()
        params = init_params(cfg, seed=0)
        otr.tracer.enable()
        eng = ServingEngine(params, cfg, batch=3, round_steps=4)
        workload = _workload(cfg)
        _submit_all(eng, workload)
        done = eng.run()
        assert len(done) == len(workload)
        # Runlog: one round event per round, the submit->admit->complete
        # narrative per request, bounded retention.
        assert len(eng.runlog.events("round")) == eng.stats.n_rounds
        assert len(eng.runlog.events("submit")) == len(workload)
        assert len(eng.runlog.events("admit")) == len(workload)
        completes = eng.runlog.events("complete")
        assert len(completes) == len(workload)
        for e in completes:
            assert e["submit_t"] <= e["admit_t"] <= e["finish_t"]
        rnd = eng.runlog.events("round")[0]
        for field in ("iters", "occupied", "live_iters", "admitted",
                      "retired", "expired", "queue_depth",
                      "wasted_row_iters"):
            assert field in rnd
        # Histograms: TTFT observed per admission, per-token latency per
        # completion — the metric registry is the engine's by default.
        snap = om.registry.snapshot()
        assert snap["histograms"]["serving_ttft_seconds"]["count"] == \
            len(workload)
        assert snap["histograms"]["serving_token_latency_seconds"][
            "count"] == len(workload)
        assert snap["counters"]["serving_completed_total"] == len(workload)
        assert snap["gauges"]["serving_queue_depth"] == 0
        assert 0 < snap["gauges"]["serving_utilization"] <= 1
        # Trace: the serving spans are on the (enabled) process tracer.
        names = {e["name"] for e in otr.tracer.events()}
        assert {"serving.submit", "serving.admit", "serving.round",
                "serving.decode_round", "serving.retire"} <= names
        # decode_round spans nest inside their round span.
        decode = next(e for e in otr.tracer.events()
                      if e["name"] == "serving.decode_round")
        assert decode["args"]["parent"] == "serving.round"

    def test_phase_timeline_attributes_every_request(self):
        # The PR-6 tentpole contract: every completed request carries a
        # contiguous phase timeline — queue_wait + admit + decode ==
        # total EXACTLY (differences of consecutive stamps on one
        # monotonic clock; the bench's 5% acceptance bound is an
        # identity here) — mirrored into the labeled
        # serving_phase_seconds histograms and the runlog's complete
        # events, with the drift ledger calibrated alongside.
        cfg = _cfg()
        params = init_params(cfg, seed=0)
        eng = ServingEngine(params, cfg, batch=3, round_steps=4)
        workload = _workload(cfg)
        _submit_all(eng, workload)
        done = eng.run()
        assert len(done) == len(workload)
        for req in done:
            ph = req.phases()
            assert ph["queue_wait"] >= 0 and ph["admit"] > 0 \
                and ph["decode"] >= 0
            assert ph["queue_wait"] + ph["admit"] + ph["decode"] \
                == pytest.approx(ph["total"], rel=1e-9, abs=1e-12)
            assert ph["total"] == pytest.approx(
                req.finish_time - req.submit_time)
            assert 0 < ph["prefill_dispatch"] <= ph["admit"] * (1 + 1e-9)
        snap = om.registry.snapshot()
        hists = snap["histograms"]
        for phase in ("queue_wait", "admit", "decode", "total"):
            series = f'serving_phase_seconds{{phase="{phase}"}}'
            assert hists[series]["count"] == len(workload), series
            # Bucket exemplars carry request ids (strings of ints).
            assert all(int(x) >= 0 for x in
                       hists[series]["exemplars"].values())
        # The runlog's per-request events carry the same attribution.
        for ev in eng.runlog.events("complete"):
            ph = ev["phases"]
            assert set(ph) >= {"queue_wait", "admit", "decode", "total"}
            assert ph["queue_wait"] + ph["admit"] + ph["decode"] \
                == pytest.approx(ph["total"], abs=5e-6)  # runlog rounds
        # Round events gained the measured-side fields the drift ledger
        # and the runlog analyzer consume.
        rnd = eng.runlog.events("round")[0]
        assert rnd["round_s"] >= rnd["decode_s"] > 0
        assert rnd["drift_decode"] > 0
        # Per-phase means ride the ledger summary.
        assert eng.stats.summary()["mean_phase_total_s"] > 0

    def test_drift_ledger_calibrates_decode_and_prefill(self):
        # The calibration ledger (stats.calibration): same shapes every
        # round on one host, so the EWMA-vs-baseline drift ratio must
        # sit well inside the [0.5, 2.0] acceptance band, with samples
        # for both op classes and gauges in the registry.
        cfg = _cfg()
        params = init_params(cfg, seed=0)
        eng = ServingEngine(params, cfg, batch=2, round_steps=4)
        workload = _workload(cfg, n=10)
        _submit_all(eng, workload)  # warmup: compiles land here
        eng.run()
        eng2 = ServingEngine(params, cfg, batch=2, round_steps=4)
        _submit_all(eng2, workload)
        eng2.run()
        summ = eng2.stats.calibration.summary()
        assert summ["decode"]["samples"] >= 5
        assert summ["prefill"]["samples"] == len(workload)
        assert 0.5 <= summ["decode"]["drift_ratio"] <= 2.0, summ
        assert summ["decode"]["sec_per_unit_ewma"] > 0
        snap = om.registry.snapshot()
        assert 'cost_model_drift_ratio{op="decode"}' in snap["gauges"]
        assert 'cost_model_drift_ratio{op="prefill"}' in snap["gauges"]
        # ... and the drain seal carries the drift block in its ledger.
        eng2.drain()
        seal = eng2.runlog.events("drain_complete")[-1]
        assert "cost_model_drift" in seal["ledger"]

    def test_engine_retains_tail_exemplars(self):
        # Slowest-k retention through the engine: completed requests'
        # phase timelines become exemplar span trees; the TTFT
        # histogram's bucket exemplars name ids whose traces exist.
        cfg = _cfg()
        params = init_params(cfg, seed=0)
        tr = Tracer(enabled=True, exemplar_k=3)
        eng = ServingEngine(params, cfg, batch=2, round_steps=4,
                            tracer=tr)
        workload = _workload(cfg, n=8)
        _submit_all(eng, workload)
        eng.run()
        exs = tr.exemplars()
        assert len(exs) == 3
        totals = [e["total_s"] for e in exs]
        assert totals == sorted(totals, reverse=True)
        for e in exs:
            names = {s["name"] for s in e["spans"]}
            # Synthesized phase segments plus the staged real spans.
            assert {"serving.phase.queue_wait", "serving.phase.admit",
                    "serving.phase.decode"} <= names
            assert "serving.admit" in names
        # Every id the TTFT buckets point at resolves to a request that
        # ran (exemplar ids are last-per-bucket, not necessarily
        # slowest-k — the histogram side holds ids, the tracer side
        # holds traces for the k slowest).
        snap = om.registry.snapshot()
        ex_ids = snap["histograms"]["serving_ttft_seconds"]["exemplars"]
        assert ex_ids and all(0 <= int(x) < len(workload)
                              for x in ex_ids.values())

    def test_steady_state_logs_zero_compiles(self):
        # Warmup engine pays (and LOGS) the round + admission compiles;
        # a second engine on the same shapes must log none — the
        # continuously-checked form of the PR-2 zero-recompile pin.
        cfg = _cfg(vocab=53)  # unique cfg: exact jit-cache deltas
        params = init_params(cfg, seed=2)
        rng = np.random.default_rng(5)
        work = [(rng.integers(0, cfg.vocab, 8), 4) for _ in range(4)]
        eng1 = ServingEngine(params, cfg, batch=2, round_steps=4)
        _submit_all(eng1, work)
        eng1.run()
        warm = eng1.runlog.events("compile")
        assert warm, "warmup compiles must be logged, not hidden"
        assert {e["entry"] for e in warm} == {
            "serving.decode_round", "serving.prefill_into_row"}
        eng2 = ServingEngine(params, cfg, batch=2, round_steps=4)
        _submit_all(eng2, work)
        eng2.run()
        assert eng2.runlog.events("compile") == []
        with eng2.watchdog.no_recompiles():
            _submit_all(eng2, work)
            eng2.run()

    def test_instrumented_round_overhead_within_5pct_of_noop(self):
        # The no-op fast path pin: the SAME instrumented engine code,
        # tracer enabled vs disabled, must stay within 5% wall-clock on
        # identical workloads — and so must the SAMPLED configuration
        # (sample_rate < 1, the high-QPS serving mode: most traces cost
        # two stack ops and a counter read). The disabled-tracer span is
        # a bare generator yield; metrics/runlog/watchdog stay on in
        # every arm (the knob under test is tracing). Measurement
        # discipline, because a 5% wall-clock bar on a shared CPU host
        # is weather: the workload carries real decode weight (long
        # rounds of a d=64 model, so spans amortize over ~6 ms
        # dispatches — enabled overhead measures ~1.5%), each trial is a
        # full run long enough (~0.12 s: steps 64-96 at max_len=128)
        # that a 1-2 ms scheduler hiccup is ~1% of the wall rather than
        # ~4% (the 0.05 s version of this trial flaked at 5-6% late in
        # full tier-1 runs on a quiet host), the arms INTERLEAVE so
        # machine drift hits all, and min-of-trials is compared (min is
        # the noise-floor estimator).
        cfg = _cfg(d_model=64, d_ff=256, max_len=128)
        params = init_params(cfg, seed=7)
        rng = np.random.default_rng(3)
        workload = [(rng.integers(0, cfg.vocab, int(s)), int(st))
                    for s, st in zip(rng.integers(4, 12, 12),
                                     rng.integers(64, 96, 12))]
        # The "on" and "sampled" arms run with exemplar retention
        # ENABLED (exemplar_k=8): the PR-6 acceptance criterion says the
        # PR-3 pin must still hold with the slowest-k reservoir active —
        # staging is per-request-span (low rate) plus one heap op per
        # completion, which must disappear into the same 5%.
        tracers = {
            "off": Tracer(enabled=False),
            "on": Tracer(enabled=True, exemplar_k=8),
            "sampled": Tracer(enabled=True, sample_rate=0.1,
                              exemplar_k=8),
        }

        def trial(tracer):
            tracer.reset()
            eng = ServingEngine(params, cfg, batch=4, round_steps=16,
                                tracer=tracer)
            _submit_all(eng, workload)
            t0 = time.perf_counter()
            eng.run()
            return time.perf_counter() - t0

        trial(tracers["off"])  # warmup: compiles out of the measurement
        times = {name: [] for name in tracers}
        # 10 interleaved trials: ~0.05 s each, and the min-of-trials
        # estimator needs enough draws to find the noise floor on a
        # shared host — 4 was observed to flake at a 7.8% "overhead"
        # that three clean re-runs put under 2%, and 6 still flaked at
        # 5-6% late in a full tier-1 run (a ~700-test process carries
        # allocator/jit-cache pressure that widens per-trial spread;
        # the same arms pass 3/3 in isolation under 2%).
        for _ in range(10):
            for name, tracer in tracers.items():
                times[name].append(trial(tracer))
        assert len(tracers["sampled"].events()) \
            < len(tracers["on"].events())
        # Two estimators, EITHER within the bar: min-of-trials (the
        # noise-floor, sharp on a quiet host but vulnerable to one
        # lucky off-arm draw) and median-of-trials (stable under load).
        # A real >5% overhead fails both; a scheduler hiccup cannot
        # fail both at once.
        med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
        t_off_min, t_off_med = min(times["off"]), med(times["off"])
        for name in ("on", "sampled"):
            ok_min = min(times[name]) <= t_off_min * 1.05
            ok_med = med(times[name]) <= t_off_med * 1.05
            assert ok_min or ok_med, (name, times)
