"""Observability subsystem tests (marlin_tpu/obs/).

The package's acceptance claims, each pinned mechanically:

* TRACE — spans nest (parent/depth recorded, time containment), the
  export is valid Chrome/Perfetto ``trace_event`` JSON (``json.load``
  round-trip, well-formed ``ph``/``ts``/``dur`` fields), and a DISABLED
  tracer records nothing.
* METRICS — labeled series, exact histogram bucket counts, and the
  Prometheus text exposition (cumulative ``le`` buckets, ``_sum``/
  ``_count``, sanitized names).
* WATCHDOG — an INDUCED retrace on a registered jitted entry point is
  caught (poll + the scoped ``no_recompiles`` assertion), and the
  ``jax.monitoring`` listener sees backend compiles where this jax
  exposes the hook.
* RUNLOG — bounded under a long run (retained events capped, lifetime
  count exact), JSONL round-trips.
* SERVING — an instrumented engine emits per-round and per-request
  events, feeds the TTFT / per-token-latency histograms, logs ZERO
  compile events in steady state, and the instrumented round stays
  within 5% of the no-op (disabled-tracer) path.
"""

import json
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from marlin_tpu.models import TransformerConfig, init_params
from marlin_tpu.obs import metrics as om
from marlin_tpu.obs import trace as otr
from marlin_tpu.obs.runlog import RunLog
from marlin_tpu.obs.trace import Tracer
from marlin_tpu.obs.watch import (CompileWatchdog, RetraceError,
                                  no_transfers)
from marlin_tpu.serving import ServingEngine


@pytest.fixture(autouse=True)
def _fresh_obs():
    om.registry.reset()
    otr.tracer.disable()
    otr.tracer.reset()
    yield
    om.registry.reset()
    otr.tracer.disable()
    otr.tracer.reset()


class TestTracer:
    def test_span_nesting_and_chrome_trace_roundtrip(self, tmp_path):
        tr = Tracer(enabled=True)
        with tr.span("outer", phase="x"):
            with tr.span("inner"):
                time.sleep(0.001)
            with tr.span("inner2"):
                pass
        path = tr.export(tmp_path / "trace.json")
        with open(path) as f:
            doc = json.load(f)  # the round-trip IS the format check
        evs = doc["traceEvents"]
        assert len(evs) == 3
        by = {e["name"]: e for e in evs}
        for e in evs:
            assert e["ph"] == "X"
            assert isinstance(e["ts"], float) and e["ts"] >= 0
            assert isinstance(e["dur"], float) and e["dur"] >= 0
            assert isinstance(e["tid"], int)
        # Nesting: both inners record outer as parent at depth 1, and sit
        # inside outer's [ts, ts + dur] interval.
        out = by["outer"]
        assert out["args"]["depth"] == 0 and out["args"]["phase"] == "x"
        for name in ("inner", "inner2"):
            e = by[name]
            assert e["args"]["parent"] == "outer"
            assert e["args"]["depth"] == 1
            assert e["ts"] >= out["ts"]
            assert e["ts"] + e["dur"] <= out["ts"] + out["dur"] + 1e-6

    def test_disabled_tracer_records_nothing(self):
        tr = Tracer(enabled=False)

        @tr.trace
        def f():
            return 41

        assert f() == 41
        with tr.span("nope"):
            pass
        assert tr.events() == []
        tr.enable()
        assert f() == 41
        (ev,) = tr.events()
        assert ev["name"].endswith("f")

    def test_bounded_events(self):
        tr = Tracer(enabled=True, max_events=8)
        for i in range(30):
            with tr.span(f"s{i}"):
                pass
        evs = tr.events()
        assert len(evs) == 8
        assert evs[-1]["name"] == "s29"  # newest retained

    def test_sample_rate_keeps_1_in_n_roots_coherently(self):
        # Sampled tracing for high-QPS serving: sample_rate=0.25 keeps
        # exactly every 4th ROOT span, deterministically, and children
        # inherit the root's decision — retention is coherent (every
        # recorded child's parent is recorded; dropped traces vanish
        # whole), so parent links never dangle in the export.
        tr = Tracer(enabled=True, sample_rate=0.25)
        for i in range(8):
            with tr.span(f"root-{i}"):
                with tr.span(f"child-{i}"):
                    with tr.span(f"grand-{i}"):
                        pass
        evs = tr.events()
        assert len(evs) == 6  # 2 of 8 traces kept, 3 spans each
        kept = {e["name"] for e in evs}
        assert kept == {"root-3", "child-3", "grand-3",
                        "root-7", "child-7", "grand-7"}
        for e in evs:
            parent = e["args"].get("parent")
            assert parent is None or parent in kept
        # reset() restarts the deterministic counter: replayable tests.
        tr.reset()
        with tr.span("again-0"):
            pass
        assert tr.events() == []

    def test_sample_rate_one_keeps_everything(self):
        tr = Tracer(enabled=True, sample_rate=1.0)
        for i in range(5):
            with tr.span(f"s{i}"):
                pass
        assert len(tr.events()) == 5

    def test_sample_rate_validation(self):
        with pytest.raises(ValueError, match="sample_rate"):
            Tracer(sample_rate=0.0)
        with pytest.raises(ValueError, match="sample_rate"):
            Tracer(sample_rate=1.5)

    def test_thread_safety_and_per_thread_nesting(self):
        tr = Tracer(enabled=True)

        def work(tag):
            for _ in range(50):
                with tr.span(f"outer-{tag}"):
                    with tr.span(f"inner-{tag}"):
                        pass

        ts = [threading.Thread(target=work, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        evs = tr.events()
        assert len(evs) == 4 * 50 * 2
        # Parent tracking is per-thread: every inner-i names outer-i,
        # never another thread's span.
        for e in evs:
            if e["name"].startswith("inner-"):
                tag = e["name"].split("-")[1]
                assert e["args"]["parent"] == f"outer-{tag}"


class TestMetrics:
    def test_labeled_counters_and_gauges(self):
        reg = om.MetricsRegistry()
        reg.counter("req_total", route="a").inc()
        reg.counter("req_total", route="a").inc(2)
        reg.counter("req_total", route="b").inc()
        reg.gauge("depth").set(3)
        snap = reg.snapshot()
        assert snap["counters"]['req_total{route="a"}'] == 3
        assert snap["counters"]['req_total{route="b"}'] == 1
        assert snap["gauges"]["depth"] == 3
        json.dumps(snap)  # snapshot is JSON-able by contract

    def test_histogram_bucket_counts(self):
        reg = om.MetricsRegistry()
        h = reg.histogram("lat", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.1, 0.5, 5.0, 50.0):
            h.observe(v)
        s = reg.snapshot()["histograms"]["lat"]
        assert s["count"] == 5
        assert s["sum"] == pytest.approx(55.65)
        assert (s["min"], s["max"]) == (0.05, 50.0)
        # observe(0.1) lands IN the le=0.1 bucket (upper bounds are
        # inclusive, the Prometheus convention).
        assert s["buckets"] == {"0.1": 2, "1.0": 1, "10.0": 1, "+Inf": 1}

    def test_kind_conflict_raises(self):
        reg = om.MetricsRegistry()
        reg.counter("x").inc()
        with pytest.raises(ValueError, match="counter"):
            reg.gauge("x")

    def test_counter_rejects_negative(self):
        reg = om.MetricsRegistry()
        with pytest.raises(ValueError, match="up"):
            reg.counter("c").inc(-1)

    def test_prometheus_exposition(self):
        reg = om.MetricsRegistry()
        reg.counter("req.total", route="a").inc(3)
        reg.gauge("depth").set(2)
        h = reg.histogram("lat", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        text = reg.prometheus()
        lines = text.splitlines()
        # Name sanitized to the Prometheus charset; TYPE headers present.
        assert "# TYPE req_total counter" in lines
        assert 'req_total{route="a"} 3' in lines
        assert "# TYPE depth gauge" in lines
        assert "depth 2" in lines
        assert "# TYPE lat histogram" in lines
        # Exposition buckets are CUMULATIVE; +Inf equals _count.
        assert 'lat_bucket{le="0.1"} 1' in lines
        assert 'lat_bucket{le="1"} 2' in lines
        assert 'lat_bucket{le="+Inf"} 3' in lines
        assert "lat_sum 5.55" in lines
        assert "lat_count 3" in lines

    def test_one_snapshot_covers_timing_shim_and_engine_series(self):
        # The dedup satellite: utils/timing writes into the SAME default
        # registry the serving engine publishes to — one snapshot, both
        # surfaces.
        from marlin_tpu.utils import timing

        with timing.timed("op.block"):
            pass
        om.registry.gauge("serving_occupancy").set(4)
        snap = om.registry.snapshot()
        assert "op.block" in snap["histograms"]
        assert snap["counters"]["op.block.calls"] == 1
        assert snap["gauges"]["serving_occupancy"] == 4
        timing.metrics.reset()


class TestWatchdog:
    def test_poll_and_scoped_check_catch_induced_retrace(self):
        f = jax.jit(lambda x: x * 2.0)
        f(jnp.ones((3,), jnp.float32))  # first compile, pre-baseline
        wd = CompileWatchdog()
        wd.register("f", f)
        f(jnp.ones((3,), jnp.float32))  # same shape: cache hit
        assert wd.poll() == []
        f(jnp.ones((2, 2), jnp.float32))  # new shape: INDUCED retrace
        (rec,) = wd.poll(rebaseline=True)
        assert rec.name == "f" and rec.new_compiles == 1
        snap = om.registry.snapshot()
        assert snap["counters"]['obs_recompiles_total{entry="f"}'] == 1
        # Scoped form: the same induction raises, naming the entry.
        with pytest.raises(RetraceError, match=r"f \(\+1\)"):
            with wd.no_recompiles():
                f(jnp.ones((4, 4), jnp.float32))
        # ... and rebaselined on exit: a clean block passes.
        with wd.no_recompiles():
            f(jnp.ones((4, 4), jnp.float32))
        assert wd.ledger().ok

    def test_register_rejects_unjitted(self):
        wd = CompileWatchdog()
        with pytest.raises(ValueError, match="_cache_size"):
            wd.register("plain", lambda x: x)

    def test_monitoring_listener_sees_backend_compile(self):
        wd = CompileWatchdog()
        if not wd.install_monitoring():
            pytest.skip("this jax has no jax.monitoring listener hook")
        try:
            before = len(wd.ledger().backend_compile_events)
            jax.jit(lambda x: x + 17.0)(jnp.ones((5,), jnp.float32))
            ledger = wd.ledger()
            assert len(ledger.backend_compile_events) > before
            assert ledger.backend_compile_seconds > 0
            assert om.registry.snapshot()["counters"][
                "obs_backend_compiles_total"] >= 1
            assert "backend compiles" in ledger.report()
        finally:
            wd.uninstall_monitoring()

    def test_no_transfers_scopes_the_guard(self):
        # CPU-backend copies are zero-copy exempt (tests/test_doctor.py),
        # so pin the plumbing: the level holds inside, restores outside.
        before = jax.config.jax_transfer_guard
        with no_transfers():
            assert jax.config.jax_transfer_guard == "disallow"
        assert jax.config.jax_transfer_guard == before


class TestRunLog:
    def test_bounded_under_long_run(self):
        log = RunLog(maxlen=16)
        for i in range(500):
            log.emit("round", round=i)
        assert len(log) == 16
        assert log.n_emitted == 500  # lifetime count stays exact
        rounds = [e["round"] for e in log.events("round")]
        assert rounds == list(range(484, 500))  # newest retained

    def test_kind_filter_and_jsonl_roundtrip(self, tmp_path):
        log = RunLog(maxlen=8)
        log.emit("round", round=0, occupied=2)
        log.emit("complete", request_id=7)
        assert [e["kind"] for e in log.events("complete")] == ["complete"]
        path = log.dump(tmp_path / "run.jsonl")
        with open(path) as f:
            lines = [json.loads(line) for line in f]
        assert len(lines) == 2
        assert lines[0]["kind"] == "round" and lines[0]["occupied"] == 2
        assert "t" in lines[0]


def _cfg(**kw):
    base = dict(vocab=48, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                max_len=96)
    base.update(kw)
    return TransformerConfig(**base)


def _submit_all(eng, workload):
    for prompt, steps in workload:
        eng.submit(prompt, steps)


def _workload(cfg, n=8, seed=13):
    rng = np.random.default_rng(seed)
    return [(rng.integers(0, cfg.vocab, int(s)), int(st))
            for s, st in zip(rng.integers(4, 14, n),
                             rng.integers(2, 18, n))]


class TestServingObservability:
    def test_engine_feeds_runlog_histograms_and_trace(self):
        cfg = _cfg()
        params = init_params(cfg, seed=0)
        otr.tracer.enable()
        eng = ServingEngine(params, cfg, batch=3, round_steps=4)
        workload = _workload(cfg)
        _submit_all(eng, workload)
        done = eng.run()
        assert len(done) == len(workload)
        # Runlog: one round event per round, the submit->admit->complete
        # narrative per request, bounded retention.
        assert len(eng.runlog.events("round")) == eng.stats.n_rounds
        assert len(eng.runlog.events("submit")) == len(workload)
        assert len(eng.runlog.events("admit")) == len(workload)
        completes = eng.runlog.events("complete")
        assert len(completes) == len(workload)
        for e in completes:
            assert e["submit_t"] <= e["admit_t"] <= e["finish_t"]
        rnd = eng.runlog.events("round")[0]
        for field in ("iters", "occupied", "live_iters", "admitted",
                      "retired", "expired", "queue_depth",
                      "wasted_row_iters"):
            assert field in rnd
        # Histograms: TTFT observed per admission, per-token latency per
        # completion — the metric registry is the engine's by default.
        snap = om.registry.snapshot()
        assert snap["histograms"]["serving_ttft_seconds"]["count"] == \
            len(workload)
        assert snap["histograms"]["serving_token_latency_seconds"][
            "count"] == len(workload)
        assert snap["counters"]["serving_completed_total"] == len(workload)
        assert snap["gauges"]["serving_queue_depth"] == 0
        assert 0 < snap["gauges"]["serving_utilization"] <= 1
        # Trace: the serving spans are on the (enabled) process tracer.
        names = {e["name"] for e in otr.tracer.events()}
        assert {"serving.submit", "serving.admit", "serving.round",
                "serving.decode_round", "serving.retire"} <= names
        # decode_round spans nest inside their round span.
        decode = next(e for e in otr.tracer.events()
                      if e["name"] == "serving.decode_round")
        assert decode["args"]["parent"] == "serving.round"

    def test_steady_state_logs_zero_compiles(self):
        # Warmup engine pays (and LOGS) the round + admission compiles;
        # a second engine on the same shapes must log none — the
        # continuously-checked form of the PR-2 zero-recompile pin.
        cfg = _cfg(vocab=53)  # unique cfg: exact jit-cache deltas
        params = init_params(cfg, seed=2)
        rng = np.random.default_rng(5)
        work = [(rng.integers(0, cfg.vocab, 8), 4) for _ in range(4)]
        eng1 = ServingEngine(params, cfg, batch=2, round_steps=4)
        _submit_all(eng1, work)
        eng1.run()
        warm = eng1.runlog.events("compile")
        assert warm, "warmup compiles must be logged, not hidden"
        assert {e["entry"] for e in warm} == {
            "serving.decode_round", "serving.prefill_into_row"}
        eng2 = ServingEngine(params, cfg, batch=2, round_steps=4)
        _submit_all(eng2, work)
        eng2.run()
        assert eng2.runlog.events("compile") == []
        with eng2.watchdog.no_recompiles():
            _submit_all(eng2, work)
            eng2.run()

    def test_instrumented_round_overhead_within_5pct_of_noop(self):
        # The no-op fast path pin: the SAME instrumented engine code,
        # tracer enabled vs disabled, must stay within 5% wall-clock on
        # identical workloads — and so must the SAMPLED configuration
        # (sample_rate < 1, the high-QPS serving mode: most traces cost
        # two stack ops and a counter read). The disabled-tracer span is
        # a bare generator yield; metrics/runlog/watchdog stay on in
        # every arm (the knob under test is tracing). Measurement
        # discipline, because a 5% wall-clock bar on a shared CPU host
        # is weather: the workload carries real decode weight (long
        # rounds of a d=64 model, so spans amortize over ~6 ms
        # dispatches — enabled overhead measures ~1.5%), each trial is a
        # full run, the arms INTERLEAVE so machine drift hits all, and
        # min-of-trials is compared (min is the noise-floor estimator).
        cfg = _cfg(d_model=64, d_ff=256)
        params = init_params(cfg, seed=7)
        rng = np.random.default_rng(3)
        workload = [(rng.integers(0, cfg.vocab, int(s)), int(st))
                    for s, st in zip(rng.integers(4, 12, 12),
                                     rng.integers(24, 40, 12))]
        tracers = {
            "off": Tracer(enabled=False),
            "on": Tracer(enabled=True),
            "sampled": Tracer(enabled=True, sample_rate=0.1),
        }

        def trial(tracer):
            tracer.reset()
            eng = ServingEngine(params, cfg, batch=4, round_steps=16,
                                tracer=tracer)
            _submit_all(eng, workload)
            t0 = time.perf_counter()
            eng.run()
            return time.perf_counter() - t0

        trial(tracers["off"])  # warmup: compiles out of the measurement
        times = {name: [] for name in tracers}
        for _ in range(4):
            for name, tracer in tracers.items():
                times[name].append(trial(tracer))
        assert len(tracers["sampled"].events()) \
            < len(tracers["on"].events())
        t_off = min(times["off"])
        for name in ("on", "sampled"):
            assert min(times[name]) <= t_off * 1.05, (name, times)
