"""Test fixtures: a virtual 8-device CPU mesh.

The reference tests run Spark with ``local[2]`` — 2 executor threads in one JVM
— so multi-node code paths (shuffles, partitioners) execute for real without a
cluster (LocalSparkContext.scala:7-22). The analogue here:
``--xla_force_host_platform_device_count=8`` gives 8 CPU devices, so every
mesh/collective path (shard_map SUMMA, psum grids, reshardings) runs for real
without a TPU pod. Golden tests compare against NumPy in float64 (the
reference's element type), so x64 is enabled.

Note: this image's sitecustomize force-registers the 'axon' TPU platform and
sets ``jax_platforms`` via jax.config (overriding the env var), so the CPU
override must also go through jax.config, after import, before first backend
use.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
jax.config.update("jax_threefry_partitionable", True)

import numpy as np
import pytest

import marlin_tpu as mt


def pytest_configure(config):
    # Suite wall-clock guard (ROADMAP item 9): tier-1 runs with
    # `-m 'not slow' --durations=25`; any test measured > 60 s CPU gets
    # @pytest.mark.slow and moves to the weekly tier. As of PR 2 the
    # durations report tops out at ~37 s (test_windowed_forward_matches_
    # banded_oracle), so nothing currently carries the mark — the
    # registration keeps `-m 'not slow'` warning-free and the policy
    # enforceable the moment a test crosses the line.
    config.addinivalue_line(
        "markers",
        "slow: test exceeding 60 s on the CPU mesh; excluded from the "
        "tier-1 run (-m 'not slow'), exercised by the weekly tier")
    config.addinivalue_line(
        "markers",
        "weekly: breadth tests (extra variant matrices, long property "
        "drives) excluded from tier-1 like slow, but kept distinct so "
        "the weekly tier can be selected precisely (-m 'slow or "
        "weekly'); pytest_collection_modifyitems folds weekly into the "
        "slow exclusion so `-m 'not slow'` needs no update")


def pytest_collection_modifyitems(config, items):
    # MARLIN_T1_SHARD=i/n splits the tier-1 suite into n stable shards
    # by MODULE (jit caches are warmed per module; splitting inside a
    # module would recompile shared fixtures in every shard). The hash
    # is content-independent (module path CRC), so a shard assignment
    # only moves when a file is added or renamed — never when tests
    # within it change. Default 1/1 = everything, byte-identical to the
    # un-sharded run.
    import zlib

    for item in items:
        # ``weekly`` rides the slow exclusion: one `-m 'not slow'`
        # invocation stays THE tier-1 command, and `-m 'slow or
        # weekly'` selects the explicit weekly tier.
        if item.get_closest_marker("weekly") \
                and not item.get_closest_marker("slow"):
            item.add_marker(pytest.mark.slow)

    shard = os.environ.get("MARLIN_T1_SHARD", "").strip()
    if not shard:
        return
    try:
        idx_s, n_s = shard.split("/")
        idx, n = int(idx_s), int(n_s)
    except ValueError:
        raise pytest.UsageError(
            f"MARLIN_T1_SHARD must look like 'i/n' (1-based), got "
            f"{shard!r}")
    if not 1 <= idx <= n:
        raise pytest.UsageError(
            f"MARLIN_T1_SHARD index {idx} outside 1..{n}")
    if n == 1:
        return
    keep, dropped = [], []
    for item in items:
        h = zlib.crc32(str(item.fspath).encode())
        if h % n == idx - 1:
            keep.append(item)
        else:
            dropped.append(item)
    items[:] = keep
    config.hook.pytest_deselected(items=dropped)


@pytest.fixture(scope="session", autouse=True)
def _setup():
    assert len(jax.devices()) == 8, "tests need the 8-device virtual CPU mesh"
    mt.set_config(default_dtype=np.float64)
    yield


# Every executable the process-global jit caches retain keeps its JIT
# code mapped (~9 memory maps each, measured); a full tier-1 run
# accumulates 60k+ maps and the 649th test's compile then hits the
# kernel's vm.max_map_count ceiling (65530 default) — mmap fails inside
# LLVM and the suite dies with a bare SIGSEGV in backend_compile,
# regardless of WHICH program happens to compile there (observed three
# times at exactly the same test index with three different programs).
# Guard: when a module ends with the map count near the ceiling, drop
# the jit caches — later modules recompile what they need (tests only
# ever assert cache DELTAS within a single test, so clearing at module
# boundaries is invisible to the compile-count pins).
_MAP_PRESSURE_LIMIT = 45_000


@pytest.fixture(scope="module", autouse=True)
def _map_pressure_guard():
    yield
    try:
        with open("/proc/self/maps") as f:
            n = sum(1 for _ in f)
    except OSError:  # non-Linux host: nothing to guard
        return
    if n > _MAP_PRESSURE_LIMIT:
        jax.clear_caches()


@pytest.fixture(scope="session")
def mesh():
    return mt.default_mesh()


@pytest.fixture()
def rng(request):
    # Function-scoped and seeded per test id: each test sees the same stream
    # on every run REGARDLESS of which other tests exist or ran first. A
    # session-scoped stream made tolerance tests fail whenever a test was
    # added earlier in collection order.
    import zlib

    seed = zlib.crc32(request.node.nodeid.encode())
    return np.random.default_rng(seed)


@pytest.fixture()
def fleet_factory(tmp_path):
    """Factory spawning a REAL fleet: N replica subprocesses (each a
    full serving/server.py stack on an ephemeral port, deterministic
    seeds — FleetConfig.replica_environ pins the same jax x64/threefry
    config this conftest sets, so subprocess output is comparable to
    in-process goldens) behind an in-process front door. Every spawned
    fleet is torn down hard at test end, pass or FAIL — a dead test
    never leaks replica processes into the next one."""
    from marlin_tpu.fleet import FleetConfig
    from marlin_tpu.fleet.server import serve_fleet

    servers = []

    def spawn(n_replicas=2, **overrides):
        overrides.setdefault("runlog_dir", str(tmp_path / "runlogs"))
        cfg = FleetConfig(
            n_replicas=n_replicas,
            d_model=overrides.pop("d_model", 32),
            n_layers=overrides.pop("n_layers", 1),
            n_heads=overrides.pop("n_heads", 2),
            vocab=overrides.pop("vocab", 64),
            max_len=overrides.pop("max_len", 128),
            batch=overrides.pop("batch", 4),
            round_steps=overrides.pop("round_steps", 4),
            seed=overrides.pop("seed", 0),
            **overrides)
        server = serve_fleet(cfg).start_background()
        servers.append(server)
        return server

    yield spawn
    for s in servers:
        try:
            s.close_now()
        except Exception:
            pass
