"""Timing/metrics subsystem tests (utils/timing.py) — the structured
replacement for the reference's ad-hoc currentTimeMillis prints
(DenseVecMatrix.scala:348-350) and MTUtils.evaluate (MTUtils.scala:218)."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from marlin_tpu.matrix.dense import DenseVecMatrix
from marlin_tpu.utils import timing


@pytest.fixture(autouse=True)
def _fresh_metrics():
    timing.metrics.reset()
    yield
    timing.metrics.reset()


class TestMetrics:
    def test_counters_and_timings(self):
        timing.metrics.incr("ops")
        timing.metrics.incr("ops", 2)
        timing.metrics.record("gemm", 0.5)
        timing.metrics.record("gemm", 1.5)
        s = timing.metrics.summary()
        assert s["counters"]["ops"] == 3
        g = s["timings"]["gemm"]
        assert g["count"] == 2
        assert g["total_s"] == pytest.approx(2.0)
        assert g["mean_s"] == pytest.approx(1.0)
        assert (g["min_s"], g["max_s"]) == (0.5, 1.5)

    def test_dump_is_json(self):
        timing.metrics.incr("x")
        parsed = json.loads(timing.metrics.dump())
        assert parsed["counters"]["x"] == 1

    def test_reset(self):
        timing.metrics.incr("x")
        timing.metrics.reset()
        assert timing.metrics.summary()["counters"] == {}


class TestTimed:
    def test_context_records(self):
        mat = DenseVecMatrix(np.ones((4, 4)))
        with timing.timed("block", mat):
            mat.add(mat)
        s = timing.metrics.summary()
        assert s["timings"]["block"]["count"] == 1
        assert s["counters"]["block.calls"] == 1

    def test_records_even_on_exception(self):
        with pytest.raises(RuntimeError):
            with timing.timed("boom"):
                raise RuntimeError("x")
        assert timing.metrics.summary()["timings"]["boom"]["count"] == 1

    def test_decorator_fences_return(self):
        @timing.timeit(name="f")
        def f():
            return jnp.ones((8, 8))

        out = f()
        assert out.shape == (8, 8)
        assert timing.metrics.summary()["timings"]["f"]["count"] == 1

    def test_timeit_increments_calls_counter_like_timed(self):
        # Satellite fix (PR 3): pre-fix, timeit recorded the timing but
        # never bumped {label}.calls — timed and timeit now share one
        # registry path, so the counter and the histogram count agree.
        @timing.timeit(name="g")
        def g():
            return jnp.ones((4,))

        g()
        g()
        s = timing.metrics.summary()
        assert s["timings"]["g"]["count"] == 2
        assert s["counters"]["g.calls"] == 2

    def test_shim_lands_in_obs_registry(self):
        # timing.Metrics is a thin shim over obs.metrics.registry: the
        # same sample is visible through the obs snapshot (and therefore
        # through every bench artifact's metrics block).
        from marlin_tpu.obs import metrics as om

        timing.metrics.record("shimmed", 0.25)
        timing.metrics.incr("shimmed.calls")
        snap = om.registry.snapshot()
        assert snap["histograms"]["shimmed"]["count"] == 1
        assert snap["histograms"]["shimmed"]["sum"] == 0.25
        assert snap["counters"]["shimmed.calls"] == 1

    def test_fence_accepts_distributed_and_raw(self):
        timing.fence(DenseVecMatrix(np.ones((3, 3))), jnp.ones(4), "not-an-array")


class TestProfileTrace:
    def test_trace_roundtrip(self, tmp_path):
        with timing.profile_trace(str(tmp_path)) as d:
            jnp.ones((16, 16)).sum().block_until_ready()
        assert d == str(tmp_path)
        # A trace directory with at least one event file appears.
        produced = list(tmp_path.rglob("*"))
        assert produced, "profiler produced no trace files"
