"""Decode buffer-donation regression tests (the copy-free half of the
skew-proof decode work).

The jitted decode entry points (transformer._decode_scan and
._speculative_loop) DONATE their KV cache (and the speculation token
buffer) and return the final state aliased to the donated input, so the
prefill -> decode handoff updates the prefill's buffers in place instead
of copying the whole cache once per dispatch. These tests pin the three
observable properties on the CPU backend:

* CONSUMED: the passed-in arrays are deleted after the call (a caller
  reusing them fails loudly, which is the documented contract).
* ALIASED, NOT COPIED: the returned cache occupies the SAME device
  buffers (``unsafe_buffer_pointer``) as the donated input — a per-step
  or per-dispatch cache copy would surface as a fresh allocation.
* ONE COMPILE: a >= 16-step generate hits the jit cache once; re-running
  adds no retrace and no additional cache-sized live buffers.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from marlin_tpu.models import (TransformerConfig, generate, init_kv_cache,
                               init_params, quantize_params_int8)
from marlin_tpu.models import transformer as tr


def _cfg(**kw):
    base = dict(vocab=48, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                max_len=96)
    base.update(kw)
    return TransformerConfig(**base)


def _pointers(cache):
    return [{k: v.unsafe_buffer_pointer() for k, v in layer.items()}
            for layer in cache]


def _cache_nbytes(cache):
    return sum(x.nbytes for layer in cache for x in layer.values())


class TestDecodeScanDonation:
    @pytest.mark.parametrize("kw", [{}, {"kv_quant": "int8"}])
    def test_cache_consumed_and_aliased_in_place(self, kw):
        cfg = _cfg(**kw)
        params = init_params(cfg, seed=0)
        if kw.get("kv_quant"):
            params = quantize_params_int8(params)
        prompt = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab, (2, 8)),
            jnp.int32)
        _, cache = tr._prefill_jit(params, prompt, cfg=cfg)
        ptrs = _pointers(cache)
        toks, out_cache = tr._decode_scan(
            params, jnp.zeros((2,), jnp.int32), jnp.int32(8), cache,
            jax.random.PRNGKey(0), cfg, 16, 0.0, 0, 0.0, None)
        # Consumed: every donated leaf (int8 slots AND f32 scales on the
        # quantized arm) is dead.
        for layer in cache:
            for name, leaf in layer.items():
                assert leaf.is_deleted(), name
        # Aliased: the 16-step loop ran inside the prefill's own buffers.
        assert _pointers(out_cache) == ptrs
        assert toks.shape == (16, 2)

    def test_second_call_adds_no_retrace_or_buffers(self):
        cfg = _cfg()
        params = init_params(cfg, seed=1)
        prompt = jnp.zeros((2, 8), jnp.int32)

        def run():
            _, cache = tr._prefill_jit(params, prompt, cfg=cfg)
            return tr._decode_scan(
                params, jnp.zeros((2,), jnp.int32), jnp.int32(8), cache,
                jax.random.PRNGKey(0), cfg, 16, 0.0, 0, 0.0, None)

        toks1, cache1 = run()
        compiles = tr._decode_scan._cache_size()
        shape = cache1[0]["k"].shape

        def live_cache_leaves():
            return sum(1 for a in jax.live_arrays()
                       if a.shape == shape and not a.is_deleted())

        before = live_cache_leaves()
        toks2, cache2 = run()
        del cache1
        # Exactly one compile served both >= 16-step decodes...
        assert tr._decode_scan._cache_size() == compiles
        # ...and steady state holds ONE cache's worth of K/V leaves: the
        # donated handoff leaves no orphaned copy behind.
        assert live_cache_leaves() == before
        np.testing.assert_array_equal(np.asarray(toks1), np.asarray(toks2))

    def test_eos_path_donates_too(self):
        cfg = _cfg()
        params = init_params(cfg, seed=2)
        cache = init_kv_cache(cfg, 3)
        ptrs = _pointers(cache)
        done0 = jnp.asarray([True, False, True])
        toks, out_cache = tr._decode_scan(
            params, jnp.zeros((3,), jnp.int32), jnp.int32(0), cache,
            jax.random.PRNGKey(0), cfg, 16, 0.0, 0, 0.0, cfg.vocab, done0)
        assert cache[0]["k"].is_deleted()
        assert _pointers(out_cache) == ptrs

    def test_compiled_temp_arena_holds_no_cache_copy(self):
        # Memory-accounting teeth for "no per-step copy": the compiled
        # 16-step loop's temp arena must hold activations, not a second
        # cache (the donated input provides the loop-carry storage).
        from marlin_tpu.utils import cost_model as cm

        cfg = _cfg()
        params = init_params(cfg, seed=0)
        cache = init_kv_cache(cfg, 2)
        rep = cm.compiled_cost(
            tr._decode_scan, params, jnp.zeros((2,), jnp.int32),
            jnp.int32(8), cache, jax.random.PRNGKey(0), cfg, 16, 0.0, 0,
            0.0, None)
        assert rep.temp_bytes <= 2.5 * _cache_nbytes(cache)


class TestSpeculativeLoopDonation:
    def test_buf_and_cache_consumed_and_aliased(self):
        cfg = _cfg()
        params = init_params(cfg, seed=3)
        prompt = jnp.asarray(np.tile([5, 9, 17, 3], 5)[None], jnp.int32)
        _, cache = tr._prefill_jit(params, prompt, cfg=cfg)
        s, steps, draft_len = prompt.shape[1], 16, 5
        buf = jnp.zeros((1, s + steps + draft_len), jnp.int32)
        buf = buf.at[:, :s].set(prompt)
        buf_ptr = buf.unsafe_buffer_pointer()
        cache_ptrs = _pointers(cache)
        out_buf, vsteps, _, out_cache = tr._speculative_loop(
            params, buf, s + 1, cache, jax.random.PRNGKey(0), cfg, steps,
            draft_len, 2, 0.0)
        assert buf.is_deleted() and cache[0]["k"].is_deleted()
        assert out_buf.unsafe_buffer_pointer() == buf_ptr
        assert _pointers(out_cache) == cache_ptrs
        assert int(jnp.max(vsteps)) >= 1

    def test_public_generate_speculative_unaffected_by_donation(self):
        # The public wrapper owns both donated buffers; repeated calls and
        # the prompt batch passed by the caller must be untouched.
        cfg = _cfg()
        params = init_params(cfg, seed=3)
        prompt = jnp.asarray(np.tile([1, 2, 3], 6)[None], jnp.int32)
        from marlin_tpu.models import generate_speculative

        a = np.asarray(generate_speculative(params, prompt, 10, cfg,
                                            draft_len=4))
        b = np.asarray(generate_speculative(params, prompt, 10, cfg,
                                            draft_len=4))
        np.testing.assert_array_equal(a, b)
        assert not prompt.is_deleted()


class TestGenerateEndToEnd:
    def test_generate_still_composes_and_prompt_survives(self):
        cfg = _cfg()
        params = init_params(cfg, seed=4)
        prompt = jnp.zeros((2, 6), jnp.int32)
        out = generate(params, prompt, 20, cfg)
        assert out.shape == (2, 20)
        # Donation consumes the internal prefill cache, never user inputs.
        assert not prompt.is_deleted()
        leaves = jax.tree.leaves(params)
        assert not any(leaf.is_deleted() for leaf in leaves)
