"""Distributed sparse layer: row-sharded COO over the 8-device mesh.

VERDICT round-1 item #2: sparse must be *actually* distributed — operands and
result spread over the mesh, no O(m*n) single-device densify. Golden pattern:
ring product vs NumPy oracle on the dense forms; sharding asserted on the
triple arrays themselves."""

import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu.matrix.dense import DenseVecMatrix
from marlin_tpu.matrix.dist_sparse import DistSparseVecMatrix
from marlin_tpu.matrix.sparse import CoordinateMatrix, SparseVecMatrix


def _random_coo(rng, m, n, density):
    mask = rng.random((m, n)) < density
    r, c = np.nonzero(mask)
    v = rng.standard_normal(r.shape[0])
    return r, c, v


def _dense(r, c, v, shape):
    a = np.zeros(shape)
    np.add.at(a, (r, c), v)
    return a


class TestDistSparseVecMatrix:
    def test_construction_shards_over_all_devices(self, rng, mesh):
        r, c, v = _random_coo(rng, 40, 32, 0.2)
        a = DistSparseVecMatrix.from_coo(r, c, v, (40, 32))
        n_dev = len(mesh.devices.flat)
        assert a.rows.shape[0] == n_dev
        # Each device holds exactly one stripe of the triples.
        assert len(a.vals.sharding.device_set) == n_dev
        assert a.nnz == len(v)
        np.testing.assert_allclose(a.to_numpy(), _dense(r, c, v, (40, 32)))

    def test_round_trip_sparse_vec_matrix(self, rng):
        r, c, v = _random_coo(rng, 24, 16, 0.15)
        svm = SparseVecMatrix.from_coo(r, c, v, (24, 16))
        dist = svm.distribute()
        back = dist.to_sparse_vec_matrix()
        np.testing.assert_allclose(back.to_numpy(), svm.to_numpy())

    @pytest.mark.parametrize("mode", ["ring", "dense", "ell"])
    @pytest.mark.parametrize("shape_a,shape_b,density", [
        ((48, 40), (40, 56), 0.15),
        ((17, 23), (23, 9), 0.3),    # uneven stripes
        ((64, 64), (64, 64), 0.02),  # sparse enough for empty stripes
    ])
    def test_multiply_sparse_vs_oracle(self, rng, shape_a, shape_b, density,
                                       mode):
        ra, ca, va = _random_coo(rng, *shape_a, density)
        rb, cb, vb = _random_coo(rng, *shape_b, density)
        a = DistSparseVecMatrix.from_coo(ra, ca, va, shape_a)
        b = DistSparseVecMatrix.from_coo(rb, cb, vb, shape_b)
        out = a.multiply_sparse(b, mode=mode)
        assert isinstance(out, CoordinateMatrix)
        oracle = _dense(ra, ca, va, shape_a) @ _dense(rb, cb, vb, shape_b)
        np.testing.assert_allclose(out.to_numpy(), oracle, rtol=1e-10, atol=1e-10)

    def test_result_triples_stay_sharded(self, rng, mesh):
        ra, ca, va = _random_coo(rng, 48, 40, 0.2)
        rb, cb, vb = _random_coo(rng, 40, 32, 0.2)
        a = DistSparseVecMatrix.from_coo(ra, ca, va, (48, 40))
        b = DistSparseVecMatrix.from_coo(rb, cb, vb, (40, 32))
        out = a.multiply_sparse(b)
        # The product's triple arrays are themselves mesh-sharded: the COO
        # result never lands on one device.
        assert len(out.values.sharding.device_set) == len(mesh.devices.flat)
        assert out.padded
        # Logical nnz excludes stripe padding.
        oracle = _dense(ra, ca, va, (48, 40)) @ _dense(rb, cb, vb, (40, 32))
        assert out.nnz == int(np.count_nonzero(oracle))

    @pytest.mark.parametrize("mode", ["ring", "dense", "ell"])
    def test_multiply_dense_vs_oracle(self, rng, mode):
        ra, ca, va = _random_coo(rng, 40, 48, 0.2)
        bd = rng.standard_normal((48, 24))
        a = DistSparseVecMatrix.from_coo(ra, ca, va, (40, 48))
        out = a.multiply_dense(DenseVecMatrix(bd), mode=mode)
        assert isinstance(out, DenseVecMatrix)
        oracle = _dense(ra, ca, va, (40, 48)) @ bd
        np.testing.assert_allclose(out.to_numpy(), oracle, rtol=1e-10, atol=1e-10)

    def test_unaligned_cap_repadded(self, mesh):
        # Direct __init__ with cap not a multiple of the entry chunk: the
        # ctor must re-pad, or entries past the last full chunk are silently
        # dropped by the chunked accumulator.
        nd = len(mesh.devices.flat)
        n = 16
        for cap in (1, 129):
            r = np.zeros((nd, cap), np.int32)
            c = np.zeros((nd, cap), np.int32)
            v = np.zeros((nd, cap))
            # One real entry per shard, in the LAST slot.
            stripe = -(-n // nd)
            for d in range(nd):
                row = min(d * stripe, n - 1)
                r[d, :] = row
                r[d, -1] = row
                c[d, -1] = row
                v[d, -1] = 1.0
            a = DistSparseVecMatrix(r, c, v, (n, n))
            eye_r, eye_c = np.arange(n), np.arange(n)
            b = DistSparseVecMatrix.from_coo(eye_r, eye_c, np.ones(n), (n, n))
            out = a.multiply_sparse(b, mode="ring")
            np.testing.assert_allclose(out.to_numpy(), a.to_numpy())

    def test_padded_to_bcoo_filters_pads(self, rng, mesh):
        ra, ca, va = _random_coo(rng, 32, 40, 0.2)
        rb, cb, vb = _random_coo(rng, 40, 24, 0.2)
        a = DistSparseVecMatrix.from_coo(ra, ca, va, (32, 40))
        b = DistSparseVecMatrix.from_coo(rb, cb, vb, (40, 24))
        out = a.multiply_sparse(b)
        svm = out.to_sparse_vec_matrix()
        oracle = _dense(ra, ca, va, (32, 40)) @ _dense(rb, cb, vb, (40, 24))
        assert svm.nnz == int(np.count_nonzero(oracle))
        np.testing.assert_allclose(svm.to_numpy(), oracle, rtol=1e-10, atol=1e-10)

    def test_dimension_mismatch_raises(self, rng):
        r, c, v = _random_coo(rng, 8, 8, 0.3)
        a = DistSparseVecMatrix.from_coo(r, c, v, (8, 8))
        b = DistSparseVecMatrix.from_coo(r, c, v, (8, 8))
        b._shape = (9, 8)
        with pytest.raises(ValueError):
            a.multiply_sparse(b)

    def test_empty_operand(self, mesh):
        a = DistSparseVecMatrix.from_coo([], [], np.zeros(0), (16, 16))
        b = DistSparseVecMatrix.from_coo([0], [0], [1.0], (16, 16))
        out = a.multiply_sparse(b)
        assert out.nnz == 0
        np.testing.assert_allclose(out.to_numpy(), np.zeros((16, 16)))

    def test_wide_k_narrow_n_chunk_padding(self, rng):
        # Regression: the kernel-chunk pad sentinel must sort AFTER every
        # real column of A (k-extent), not after the OUTPUT width n. With
        # K >> n and a cap that doesn't divide the budget-sized chunk, a
        # sentinel of n would land mid-range, break the column-sorted
        # invariant, and silently drop contributions via the searchsorted
        # hop bounds.
        m, k, n = 64, 4096, 32
        nnz = 3000  # cap 3072 -> chunk padding path taken
        ra = rng.integers(0, m, nnz)
        ca = rng.integers(0, k, nnz)
        va = rng.standard_normal(nnz)
        rb = rng.integers(0, k, nnz)
        cb = rng.integers(0, n, nnz)
        vb = rng.standard_normal(nnz)
        a = DistSparseVecMatrix.from_coo(ra, ca, va, (m, k))
        b = DistSparseVecMatrix.from_coo(rb, cb, vb, (k, n))
        oracle = _dense(ra, ca, va, (m, k)) @ _dense(rb, cb, vb, (k, n))
        np.testing.assert_allclose(
            a.multiply_sparse(b, mode="ring").to_numpy(), oracle,
            rtol=1e-10, atol=1e-10
        )


class TestSparseVecMatrixRouting:
    def test_multiply_sparse_routes_distributed(self, rng, mesh):
        # The legacy single-BCOO type's sparse x sparse now runs the ring
        # engine and returns mesh-sharded triples (round-1 VERDICT: the old
        # path densified O(m*n) on one device).
        ra, ca, va = _random_coo(rng, 32, 40, 0.2)
        rb, cb, vb = _random_coo(rng, 40, 24, 0.2)
        a = SparseVecMatrix.from_coo(ra, ca, va, (32, 40))
        b = SparseVecMatrix.from_coo(rb, cb, vb, (40, 24))
        out = a.multiply_sparse(b)
        assert isinstance(out, CoordinateMatrix)
        assert len(out.values.sharding.device_set) == len(mesh.devices.flat)
        oracle = _dense(ra, ca, va, (32, 40)) @ _dense(rb, cb, vb, (40, 24))
        np.testing.assert_allclose(out.to_numpy(), oracle, rtol=1e-10, atol=1e-10)

    def test_coordinate_to_dist_sparse(self, rng):
        r, c, v = _random_coo(rng, 20, 20, 0.2)
        coo = CoordinateMatrix(r, c, v, shape=(20, 20))
        dist = coo.to_dist_sparse()
        np.testing.assert_allclose(dist.to_numpy(), _dense(r, c, v, (20, 20)))


class TestPaddedCoordinateConsumers:
    def test_als_on_padded_result_ignores_pads(self, rng):
        # Regression: a padded CoordinateMatrix (the ring product's output)
        # must not feed its value-0 pad slots to ALS as real (0, 0, 0.0)
        # observations — they piled phantom normal-equation terms onto
        # user 0 / product 0.
        ra, ca, va = _random_coo(rng, 24, 16, 0.3)
        rb, cb, vb = _random_coo(rng, 16, 12, 0.3)
        a = DistSparseVecMatrix.from_coo(ra, ca, np.abs(va) + 0.5, (24, 16))
        b = DistSparseVecMatrix.from_coo(rb, cb, np.abs(vb) + 0.5, (16, 12))
        padded = a.multiply_sparse(b)
        assert padded.padded and padded.values.shape[0] > padded.nnz
        r, c, v = padded.compact_triples()
        compacted = CoordinateMatrix(r, c, v, shape=padded.shape)
        uf_p, pf_p = padded.als(rank=3, iterations=3, seed=7)
        uf_c, pf_c = compacted.als(rank=3, iterations=3, seed=7)
        np.testing.assert_allclose(uf_p.to_numpy(), uf_c.to_numpy(), rtol=1e-8)
        np.testing.assert_allclose(pf_p.to_numpy(), pf_c.to_numpy(), rtol=1e-8)

    def test_compact_triples_single_filter_point(self, rng):
        r = np.array([3, 0, 7]); c = np.array([1, 0, 2]); v = np.array([2.0, 0.0, 1.0])
        coo = CoordinateMatrix(r, c, v, shape=(8, 8), padded=True)
        rr, cc, vv = coo.compact_triples()
        assert list(vv) == [2.0, 1.0]
        # Unpadded matrices pass through untouched (explicit zeros kept).
        coo2 = CoordinateMatrix(r, c, v, shape=(8, 8), padded=False)
        assert len(coo2.compact_triples()[2]) == 3


class TestDenseRoute:
    """Auto-dispatch between the dense MXU ring and the gather ring (the
    TPU-native counterpart of the reference's densify-then-multiply
    SparseMultiply modes)."""

    def test_auto_picks_dense_when_it_fits(self, rng):
        r, c, v = _random_coo(rng, 32, 32, 0.2)
        a = DistSparseVecMatrix.from_coo(r, c, v, (32, 32))
        assert a._use_dense_route(32, 32, "auto")

    def test_auto_falls_back_to_ring_over_budget(self, rng, monkeypatch):
        import marlin_tpu.matrix.dist_sparse as ds

        monkeypatch.setattr(ds, "_DENSIFY_BUDGET_BYTES", 0)
        r, c, v = _random_coo(rng, 32, 32, 0.2)
        a = DistSparseVecMatrix.from_coo(r, c, v, (32, 32))
        assert not a._use_dense_route(32, 32, "auto")
        # And the product through auto still matches the oracle.
        b = DistSparseVecMatrix.from_coo(r, c, v, (32, 32))
        oracle = _dense(r, c, v, (32, 32)) @ _dense(r, c, v, (32, 32))
        np.testing.assert_allclose(
            a.multiply_sparse(b).to_numpy(), oracle, rtol=1e-10, atol=1e-10)

    def test_unknown_mode_raises(self, rng):
        r, c, v = _random_coo(rng, 8, 8, 0.3)
        a = DistSparseVecMatrix.from_coo(r, c, v, (8, 8))
        with pytest.raises(ValueError, match="mode"):
            a.multiply_sparse(a, mode="bogus")

    def test_densify_stripes_matches_to_numpy(self, rng, mesh):
        r, c, v = _random_coo(rng, 20, 12, 0.3)
        a = DistSparseVecMatrix.from_coo(r, c, v, (20, 12))
        stripes = np.asarray(a.densify_stripes())
        # Row-sharded over the mesh; rows past num_rows are stripe padding.
        assert len(a.vals.sharding.device_set) == len(mesh.devices.flat)
        np.testing.assert_allclose(stripes[:20], a.to_numpy())
        assert not stripes[20:].any()

    def test_dense_route_duplicate_entries_add(self, rng):
        # densify uses scatter-add: duplicate COO entries must sum, same
        # as the gather ring and to_numpy.
        r = np.array([0, 0, 1]); c = np.array([1, 1, 0])
        v = np.array([2.0, 3.0, 1.0])
        a = DistSparseVecMatrix.from_coo(r, c, v, (4, 4))
        eye = DistSparseVecMatrix.from_coo(
            np.arange(4), np.arange(4), np.ones(4), (4, 4))
        out = a.multiply_sparse(eye, mode="dense")
        np.testing.assert_allclose(out.to_numpy(), a.to_numpy())

    def test_dense_route_empty_operand(self):
        a = DistSparseVecMatrix.from_coo([], [], np.zeros(0), (16, 16))
        b = DistSparseVecMatrix.from_coo([0], [0], [1.0], (16, 16))
        out = a.multiply_sparse(b, mode="dense")
        assert out.nnz == 0


class TestEllRoute:
    """ELL row-gather engine (the low-density arm) + the lazy result."""

    def test_auto_picks_ell_at_low_density(self, rng):
        n = 64
        r, c, v = _random_coo(rng, n, n, 0.003)  # under the 5e-3 ceiling
        a = DistSparseVecMatrix.from_coo(r, c, v, (n, n))
        assert a._ell_wins(n, n)
        b = DistSparseVecMatrix.from_coo(r, c, v, (n, n))
        oracle = _dense(r, c, v, (n, n)) @ _dense(r, c, v, (n, n))
        np.testing.assert_allclose(a.multiply_sparse(b).to_numpy(), oracle,
                                   rtol=1e-10, atol=1e-10)

    def test_density_gate(self, rng):
        n = 64
        r, c, v = _random_coo(rng, n, n, 0.2)
        a = DistSparseVecMatrix.from_coo(r, c, v, (n, n))
        assert not a._ell_wins(n, n)  # 20% density: dense ring territory

    def test_skew_guard(self):
        # One dense-ish row among empties: r_slots blows past 8*mean + 32.
        n = 512
        cols = np.arange(n)
        rows = np.zeros(n, np.int64)
        a = DistSparseVecMatrix.from_coo(rows, cols, np.ones(n), (n, n))
        assert not a._ell_wins(n, n)
        # Forced ELL still computes the right answer.
        b = DistSparseVecMatrix.from_coo(
            np.arange(n), np.arange(n), np.ones(n), (n, n))
        out = a.multiply_sparse(b, mode="ell")
        np.testing.assert_allclose(out.to_numpy(), a.to_numpy())

    def test_lazy_result_defers_extraction(self, rng, mesh):
        ra, ca, va = _random_coo(rng, 48, 40, 0.1)
        rb, cb, vb = _random_coo(rng, 40, 32, 0.1)
        a = DistSparseVecMatrix.from_coo(ra, ca, va, (48, 40))
        b = DistSparseVecMatrix.from_coo(rb, cb, vb, (40, 32))
        out = a.multiply_sparse(b, mode="ell")
        oracle = _dense(ra, ca, va, (48, 40)) @ _dense(rb, cb, vb, (40, 32))
        # nnz comes from the fused count — no triple extraction yet.
        assert out.nnz == int(np.count_nonzero(oracle))
        assert out._triples is None
        # Densify straight from the product stripes, still no extraction.
        np.testing.assert_allclose(out.to_numpy(), oracle, rtol=1e-10,
                                   atol=1e-10)
        assert out._triples is None
        # First triple read materializes sharded padded triples.
        vals = out.values
        assert out._triples is not None
        assert len(vals.sharding.device_set) == len(mesh.devices.flat)
        r2, c2, v2 = out.compact_triples()
        got = np.zeros(out.shape)
        np.add.at(got, (r2, c2), v2)
        np.testing.assert_allclose(got, oracle, rtol=1e-10, atol=1e-10)

    def test_materialize_releases_dense_stripes(self, rng):
        # ADVICE r04: the lazy result pins the (m x n) dense stripes until
        # the triples are first read; materialize() is the explicit release
        # for memory-sensitive callers — idempotent, chains, and the data
        # survives the handoff.
        ra, ca, va = _random_coo(rng, 48, 40, 0.1)
        rb, cb, vb = _random_coo(rng, 40, 32, 0.1)
        a = DistSparseVecMatrix.from_coo(ra, ca, va, (48, 40))
        b = DistSparseVecMatrix.from_coo(rb, cb, vb, (40, 32))
        out = a.multiply_sparse(b, mode="ell")
        assert out._dense is not None
        assert out.materialize() is out
        assert out._dense is None and out._triples is not None
        assert out.materialize() is out  # idempotent
        oracle = _dense(ra, ca, va, (48, 40)) @ _dense(rb, cb, vb, (40, 32))
        np.testing.assert_allclose(out.to_numpy(), oracle, rtol=1e-10,
                                   atol=1e-10)
        assert out.nnz == int(np.count_nonzero(oracle))

    def test_ell_duplicate_entries_add(self):
        r = np.array([0, 0, 1]); c = np.array([1, 1, 0])
        v = np.array([2.0, 3.0, 1.0])
        a = DistSparseVecMatrix.from_coo(r, c, v, (4, 4))
        eye = DistSparseVecMatrix.from_coo(
            np.arange(4), np.arange(4), np.ones(4), (4, 4))
        out = a.multiply_sparse(eye, mode="ell")
        np.testing.assert_allclose(out.to_numpy(), a.to_numpy())

    def test_ell_empty_operand(self):
        a = DistSparseVecMatrix.from_coo([], [], np.zeros(0), (16, 16))
        b = DistSparseVecMatrix.from_coo([0], [0], [1.0], (16, 16))
        out = a.multiply_sparse(b, mode="ell")
        assert out.nnz == 0
        np.testing.assert_allclose(out.to_numpy(), np.zeros((16, 16)))


class TestHopBounding:
    def test_entries_sorted_by_column_per_stripe(self, rng):
        r, c, v = _random_coo(rng, 40, 64, 0.3)
        a = DistSparseVecMatrix.from_coo(r, c, v, (40, 64))
        cols = np.asarray(a.cols)
        assert all(np.all(np.diff(row) >= 0) for row in cols)

    def test_product_correct_when_columns_span_all_stripes(self, rng):
        # Entries in every k-stripe of every output stripe: the searchsorted
        # chunk bounds must not skip boundary chunks.
        m = k = n = 64
        ra, ca, va = _random_coo(rng, m, k, 0.5)
        rb, cb, vb = _random_coo(rng, k, n, 0.5)
        a = DistSparseVecMatrix.from_coo(ra, ca, va, (m, k))
        b = DistSparseVecMatrix.from_coo(rb, cb, vb, (k, n))
        out = a.multiply_sparse(b, mode="ring")
        oracle = _dense(ra, ca, va, (m, k)) @ _dense(rb, cb, vb, (k, n))
        np.testing.assert_allclose(out.to_numpy(), oracle, rtol=1e-10, atol=1e-10)


class TestOutputDtypeContract:
    def test_bf16_operands_keep_bf16_results(self, rng, mesh):
        # The engines accumulate in f32 internally but cast back at the
        # boundary — bf16 in, bf16 out (the framework's cast-back-once
        # convention).
        import jax.numpy as jnp
        import ml_dtypes

        bf16 = ml_dtypes.bfloat16
        r, c, v = _random_coo(rng, 24, 16, 0.3)
        rb, cb, vb = _random_coo(rng, 16, 12, 0.3)
        a = DistSparseVecMatrix.from_coo(r, c, v.astype(bf16), (24, 16))
        b = DistSparseVecMatrix.from_coo(rb, cb, vb.astype(bf16), (16, 12))
        assert a.vals.dtype == jnp.bfloat16
        out = a.multiply_sparse(b)
        assert out.values.dtype == jnp.bfloat16
        out_ell = a.multiply_sparse(b, mode="ell")
        assert out_ell.values.dtype == jnp.bfloat16
        dm = DenseVecMatrix(
            jnp.asarray(rng.standard_normal((16, 6)), jnp.bfloat16)
        )
        out2 = a.multiply_dense(dm)
        assert out2.dtype == jnp.bfloat16

    def test_multi_chunk_path_forced_by_small_budget(self, rng, monkeypatch):
        # With the default 256 MB budget, test-size matrices always get
        # chunk == cap (single-chunk); shrink the budget so the kernels run
        # the multi-chunk searchsorted hop-bounding path, and clear the
        # engine caches so the kernels rebuild under the patched budget.
        import marlin_tpu.matrix.dist_sparse as ds

        monkeypatch.setattr(ds, "_CHUNK_BUDGET_BYTES", 128 * 64 * 4)
        ds._spsp_ring.cache_clear()
        ds._spmm_ring_dense.cache_clear()
        try:
            m = k = n = 64
            ra, ca, va = _random_coo(rng, m, k, 0.5)  # ~2k entries: cap 2048
            rb, cb, vb = _random_coo(rng, k, n, 0.5)
            a = DistSparseVecMatrix.from_coo(ra, ca, va, (m, k))
            b = DistSparseVecMatrix.from_coo(rb, cb, vb, (k, n))
            assert ds._kernel_chunk(a.rows.shape[1], n) < a.rows.shape[1]
            oracle = _dense(ra, ca, va, (m, k)) @ _dense(rb, cb, vb, (k, n))
            np.testing.assert_allclose(
                a.multiply_sparse(b, mode="ring").to_numpy(), oracle,
                rtol=1e-10, atol=1e-10)
            # sparse x dense through the same chunk loop
            import jax.numpy as jnp

            dm = DenseVecMatrix(
                jnp.asarray(rng.standard_normal((k, 24)), jnp.float64))
            got = a.multiply_dense(dm, mode="ring").to_numpy()
            ref = _dense(ra, ca, va, (m, k)) @ np.asarray(dm.to_numpy())
            np.testing.assert_allclose(got, ref, rtol=1e-8, atol=1e-8)
        finally:
            ds._spsp_ring.cache_clear()
            ds._spmm_ring_dense.cache_clear()


class TestEllUnderJit:
    def test_spmm_ell_route_inside_jit_with_grad(self):
        # GCN-shaped usage at ELL-eligible density: spmm inside a jitted
        # loss, gradient through the custom vjp (cached-transpose engine),
        # with the route pick + ELL build happening under the trace.
        import jax
        import jax.numpy as jnp

        n, f = 1024, 8
        rng = np.random.default_rng(11)
        nnz = 2000  # density ~0.002 < the 5e-3 ELL ceiling
        r = rng.integers(0, n, nnz)
        c = rng.integers(0, n, nnz)
        v = rng.standard_normal(nnz)
        a = DistSparseVecMatrix.from_coo(r, c, v, (n, n))
        assert a._ell_wins(n, f)
        from marlin_tpu.matrix.dist_sparse import spmm

        b = jnp.asarray(rng.standard_normal((n, f)))

        @jax.jit
        def loss(b):
            return jnp.sum(spmm(a, b) ** 2)

        g = jax.jit(jax.grad(loss))(b)
        da = _dense(r, c, v, (n, n))
        ref = 2.0 * da.T @ (da @ np.asarray(b))
        np.testing.assert_allclose(np.asarray(g), ref, rtol=1e-8, atol=1e-8)
