"""tools/runlog_report.py — the offline latency-attribution analyzer
(docs/observability.md §7).

Two layers, each pinned:

* SYNTHETIC runlogs: the anomaly detectors fire on exactly the injected
  defect — a steady-state compile (and NOT a warmup or novel-bucket
  one), a round that sat on ready work, a deadline expiry, a phase sum
  that disagrees with the measured wall-clock, an unresolved request in
  a sealed log — and stay silent on a clean narrative.
* A REAL engine runlog (in-process drain to a file sink): the report
  parses, joins every request's timeline, finds zero anomalies, and the
  phase-sum identity holds. The tier-1 subprocess form of this smoke
  (a SIGTERM'd real server) lives in tests/test_frontend.py.
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

from marlin_tpu.models import TransformerConfig, init_params
from marlin_tpu.obs.runlog import RunLog
from marlin_tpu.serving import ServingEngine

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def rr():
    spec = importlib.util.spec_from_file_location(
        "runlog_report", os.path.join(_REPO, "tools",
                                      "runlog_report.py"))
    mod = importlib.util.module_from_spec(spec)
    # Register BEFORE exec (the importlib contract): dataclasses in a
    # by-path module resolve string annotations via sys.modules
    # (marlint exec-loader).
    sys.modules["runlog_report"] = mod
    spec.loader.exec_module(mod)
    return mod


def _write(tmp_path, events, name="runlog.jsonl"):
    path = tmp_path / name
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")
    return str(path)


def _clean_events():
    """A minimal clean narrative: one engine, two requests, two rounds,
    warmup compiles at the admission round, sealed drain."""
    return [
        {"kind": "engine_start", "t": 0.0, "batch": 2, "round_steps": 4,
         "prefill_chunk": None, "max_pending": 8, "max_len": 64,
         "prefix_cache": False},
        {"kind": "submit", "t": 0.01, "request_id": 0, "prompt_len": 8,
         "steps": 4, "round": 0, "queue_depth": 1},
        {"kind": "submit", "t": 0.011, "request_id": 1, "prompt_len": 24,
         "steps": 4, "round": 0, "queue_depth": 2},
        {"kind": "admit", "t": 0.02, "request_id": 0, "row": 0,
         "round": 0, "prompt_len": 8, "wait_rounds": 0, "queue_depth": 1},
        {"kind": "admit", "t": 0.03, "request_id": 1, "row": 1,
         "round": 0, "prompt_len": 24, "wait_rounds": 0,
         "queue_depth": 0},
        {"kind": "compile", "t": 0.04, "round": 0,
         "entry": "serving.decode_round", "new_compiles": 1},
        {"kind": "compile", "t": 0.04, "round": 0,
         "entry": "serving.prefill_into_row", "new_compiles": 2},
        {"kind": "round", "t": 0.05, "round": 0, "iters": 4,
         "occupied": 2, "live_iters": 8, "admitted": 2, "retired": 0,
         "expired": 0, "prefilling": 0, "queue_depth": 0,
         "wasted_row_iters": 0, "round_s": 0.04, "decode_s": 0.03,
         "drift_decode": 1.0},
        {"kind": "complete", "t": 0.09, "request_id": 0, "row": 0,
         "emitted": 4, "live_iters": 4, "submit_t": 1.00,
         "admit_t": 1.01, "finish_t": 1.09, "rounds": 2,
         "phases": {"queue_wait": 0.005, "admit": 0.005,
                    "decode": 0.08, "total": 0.09}},
        {"kind": "complete", "t": 0.095, "request_id": 1, "row": 1,
         "emitted": 4, "live_iters": 4, "submit_t": 1.001,
         "admit_t": 1.02, "finish_t": 1.095, "rounds": 2,
         "phases": {"queue_wait": 0.009, "admit": 0.01,
                    "decode": 0.075, "total": 0.094}},
        {"kind": "round", "t": 0.1, "round": 1, "iters": 4,
         "occupied": 2, "live_iters": 4, "admitted": 0, "retired": 2,
         "expired": 0, "prefilling": 0, "queue_depth": 0,
         "wasted_row_iters": 4, "round_s": 0.05, "decode_s": 0.045,
         "drift_decode": 1.02},
        {"kind": "drain_complete", "t": 0.11, "round": 2,
         "ledger": {"completed": 2, "admitted": 2}},
    ]


class TestSyntheticRunlogs:
    def test_clean_log_reports_ok(self, rr, tmp_path):
        report = rr.build_report(rr.load_runlog(
            _write(tmp_path, _clean_events())))
        assert report["ok"] is True and report["anomalies"] == []
        assert report["sealed"] is True
        assert report["n_submitted"] == report["n_completed"] == 2
        assert report["post_warmup_compiles"] == 0
        assert report["phase_sum_checked"] == 2
        assert report["phase_sum_max_rel_err"] <= 0.05
        assert report["ledger"]["completed"] == 2
        # Per-request timelines joined across event kinds.
        r0 = next(r for r in report["requests"]
                  if r["request_id"] == 0)
        assert r0["status"] == "done" and r0["prompt_len"] == 8
        assert r0["e2e_s"] == pytest.approx(0.09)
        # Per-round series summarized (batch from engine_start).
        assert report["rounds"]["n_rounds"] == 2
        assert report["rounds"]["batch"] == 2
        assert report["rounds"]["utilization"] == pytest.approx(
            12 / 16)
        assert report["rounds"]["drift_decode_last"] == 1.02

    def test_steady_state_compile_is_flagged(self, rr, tmp_path):
        events = _clean_events()
        events.insert(-1, {"kind": "compile", "t": 0.10, "round": 1,
                           "entry": "serving.decode_round",
                           "new_compiles": 1})
        report = rr.build_report(rr.load_runlog(_write(tmp_path, events)))
        assert report["ok"] is False
        (a,) = report["anomalies"]
        assert a["kind"] == "post_warmup_compile"
        assert a["entry"] == "serving.decode_round" and a["round"] == 1
        assert report["post_warmup_compiles"] == 1

    def test_novel_bucket_compile_is_warmup_not_anomaly(self, rr,
                                                        tmp_path):
        # A SECOND prefill compile is fine when that round admitted a
        # never-seen 16-bucket (one compile per distinct bucket is the
        # contract); the same compile without a novel bucket is not.
        events = _clean_events()
        tail = [
            {"kind": "submit", "t": 0.12, "request_id": 2,
             "prompt_len": 40, "steps": 2, "round": 2,
             "queue_depth": 1},
            {"kind": "admit", "t": 0.13, "request_id": 2, "row": 0,
             "round": 2, "prompt_len": 40, "wait_rounds": 2,
             "queue_depth": 0},
            {"kind": "compile", "t": 0.14, "round": 2,
             "entry": "serving.prefill_into_row", "new_compiles": 1},
            {"kind": "round", "t": 0.15, "round": 2, "iters": 2,
             "occupied": 1, "live_iters": 2, "admitted": 1,
             "retired": 1, "expired": 0, "prefilling": 0,
             "queue_depth": 0, "wasted_row_iters": 2},
            {"kind": "complete", "t": 0.16, "request_id": 2, "row": 0,
             "emitted": 2, "live_iters": 2, "submit_t": 1.2,
             "admit_t": 1.3, "finish_t": 1.4, "rounds": 1,
             "phases": {"queue_wait": 0.09, "admit": 0.01,
                        "decode": 0.1, "total": 0.2}},
        ]
        events[-1:-1] = tail  # before the drain seal
        report = rr.build_report(rr.load_runlog(_write(tmp_path, events)))
        assert report["ok"] is True, report["anomalies"]
        # Same events, but request 2 re-uses a seen bucket (8 -> 16,
        # same as request 0): now the compile has no excuse.
        for ev in tail:
            if "prompt_len" in ev:
                ev["prompt_len"] = 8
        report2 = rr.build_report(
            rr.load_runlog(_write(tmp_path, events, "r2.jsonl")))
        assert report2["ok"] is False
        assert report2["anomalies"][0]["kind"] == "post_warmup_compile"

    def test_queue_stall_deadline_and_phase_mismatch(self, rr,
                                                     tmp_path):
        events = _clean_events()
        extra = [
            # Stall PAIR: round 2 ends with work queued and a free row
            # (alone, that's a legal mid-round submission), then round 3
            # neither admits, prefills, nor expires — the scheduler sat
            # on ready work for a full round, and round 3 is flagged.
            {"kind": "round", "t": 0.105, "round": 2, "iters": 4,
             "occupied": 1, "live_iters": 4, "admitted": 0,
             "retired": 0, "expired": 0, "prefilling": 0,
             "queue_depth": 3, "wasted_row_iters": 4},
            {"kind": "round", "t": 0.107, "round": 3, "iters": 4,
             "occupied": 1, "live_iters": 4, "admitted": 0,
             "retired": 0, "expired": 0, "prefilling": 0,
             "queue_depth": 3, "wasted_row_iters": 4},
            {"kind": "timeout", "t": 0.108, "request_id": 7,
             "round": 3, "deadline_rounds": 0, "wait_s": 0.5},
            {"kind": "submit", "t": 0.1055, "request_id": 7,
             "prompt_len": 8, "steps": 2, "round": 2, "queue_depth": 4},
        ]
        events[-1:-1] = extra
        # ... and corrupt one phase block.
        events[8]["phases"]["decode"] = 0.5  # sum no longer == total
        report = rr.build_report(rr.load_runlog(_write(tmp_path, events)))
        kinds = sorted(a["kind"] for a in report["anomalies"])
        assert kinds == ["deadline_expiry", "phase_sum_mismatch",
                         "queue_stall"]
        assert report["ok"] is False
        mism = next(a for a in report["anomalies"]
                    if a["kind"] == "phase_sum_mismatch")
        assert mism["request_id"] == 0 and mism["rel_err"] > 0.05

    def test_mid_round_submission_is_not_a_stall(self, rr, tmp_path):
        # A round that ENDS with queued work and a free row is normal
        # when the submission landed mid-round (round events stamp
        # queue depth at round end); the next round admits it. Only a
        # following round that does nothing makes it a stall.
        events = _clean_events()
        extra = [
            {"kind": "submit", "t": 0.104, "request_id": 3,
             "prompt_len": 8, "steps": 2, "round": 2, "queue_depth": 1},
            {"kind": "round", "t": 0.105, "round": 2, "iters": 4,
             "occupied": 1, "live_iters": 4, "admitted": 0,
             "retired": 0, "expired": 0, "prefilling": 0,
             "queue_depth": 1, "wasted_row_iters": 4},
            {"kind": "admit", "t": 0.106, "request_id": 3, "row": 0,
             "round": 3, "prompt_len": 8, "wait_rounds": 1,
             "queue_depth": 0},
            {"kind": "round", "t": 0.107, "round": 3, "iters": 2,
             "occupied": 2, "live_iters": 4, "admitted": 1,
             "retired": 1, "expired": 0, "prefilling": 0,
             "queue_depth": 0, "wasted_row_iters": 0},
            {"kind": "complete", "t": 0.108, "request_id": 3, "row": 0,
             "emitted": 2, "live_iters": 2, "submit_t": 1.104,
             "admit_t": 1.106, "finish_t": 1.108, "rounds": 1,
             "phases": {"queue_wait": 0.001, "admit": 0.001,
                        "decode": 0.002, "total": 0.004}},
        ]
        events[-1:-1] = extra  # before the drain seal
        report = rr.build_report(rr.load_runlog(_write(tmp_path, events)))
        assert report["ok"] is True, report["anomalies"]

    def test_page_pressure_is_not_a_stall_and_pages_are_narrated(
            self, rr, tmp_path):
        # PAGED engine (PR 9): a round pair that sits on ready work
        # with a free ROW is legal when the PAGE pool couldn't fit a
        # worst-case reservation (pages_free < max_len/16 = 4 here) —
        # the same pair WITH enough free pages stays a stall. The round
        # series also narrates the page ledger.
        stall_pair = [
            {"kind": "round", "t": 0.105, "round": 2, "iters": 4,
             "occupied": 1, "live_iters": 4, "admitted": 0,
             "retired": 0, "expired": 0, "prefilling": 0,
             "queue_depth": 3, "wasted_row_iters": 4,
             "pages_used": 6, "pages_free": 2, "pages_aliased": 3,
             "page_fragmentation": 0.25},
            {"kind": "round", "t": 0.107, "round": 3, "iters": 4,
             "occupied": 1, "live_iters": 4, "admitted": 0,
             "retired": 0, "expired": 0, "prefilling": 0,
             "queue_depth": 3, "wasted_row_iters": 4,
             "pages_used": 6, "pages_free": 2, "pages_aliased": 3,
             "page_fragmentation": 0.25},
        ]
        events = _clean_events()
        events[0] = dict(events[0], kv_pages=8, prefix_sharing=True)
        events[-1:-1] = stall_pair
        report = rr.build_report(rr.load_runlog(_write(tmp_path, events)))
        assert not [a for a in report["anomalies"]
                    if a["kind"] == "queue_stall"], report["anomalies"]
        kp = report["rounds"]["kv_pages"]
        assert kp["pages_used_max"] == 6
        assert kp["pages_aliased_max"] == 3
        assert kp["fragmentation_max"] == 0.25
        # Same narrative with ROOM in the pool: the stall is real.
        events2 = _clean_events()
        events2[0] = dict(events2[0], kv_pages=8, prefix_sharing=True)
        roomy = [dict(ev, pages_free=6, pages_used=2)
                 for ev in stall_pair]
        events2[-1:-1] = roomy
        report2 = rr.build_report(rr.load_runlog(_write(tmp_path,
                                                        events2)))
        assert [a for a in report2["anomalies"]
                if a["kind"] == "queue_stall"]
        # A pool SMALLER than one worst-case reservation (kv_pages=3 <
        # max_len/16=4) clamps the bar to the pool size: an all-free
        # pool that still admits nothing is a provable stall — the
        # detector must not go permanently blind on small pools.
        events3 = _clean_events()
        events3[0] = dict(events3[0], kv_pages=3, prefix_sharing=True)
        tiny = [dict(ev, pages_free=3, pages_used=0)
                for ev in stall_pair]
        events3[-1:-1] = tiny
        report3 = rr.build_report(rr.load_runlog(_write(tmp_path,
                                                        events3)))
        assert [a for a in report3["anomalies"]
                if a["kind"] == "queue_stall"]

    def test_host_tier_rounds_are_narrated(self, rr, tmp_path):
        # Host-memory KV tier (ISSUE 16, docs/serving.md §6): rounds
        # from a tiered engine carry per-round spill/restore deltas and
        # the host ledger — the report totals them and keeps the
        # host-bytes watermark, so a sealed log answers "did the warm
        # set earn its keep" offline. An untiered paged log must NOT
        # grow the keys: their absence is how a reader tells the two
        # configurations apart.
        events = _clean_events()
        events[0] = dict(events[0], kv_pages=8, prefix_sharing=True,
                         host_kv_bytes=1 << 20)
        for ev in events:
            if ev["kind"] != "round":
                continue
            if ev["round"] == 0:
                ev.update(pages_used=6, pages_free=2, pages_aliased=0,
                          page_fragmentation=0.0, spills=2, restores=0,
                          host_bytes=8192, host_entries=2)
            else:
                ev.update(pages_used=4, pages_free=4, pages_aliased=0,
                          page_fragmentation=0.0, spills=0, restores=1,
                          host_bytes=4096, host_entries=1)
        report = rr.build_report(rr.load_runlog(_write(tmp_path, events)))
        assert report["ok"] is True, report["anomalies"]
        kp = report["rounds"]["kv_pages"]
        assert kp["spills_total"] == 2
        assert kp["restores_total"] == 1
        assert kp["host_bytes_max"] == 8192
        assert kp["host_bytes_last"] == 4096
        assert kp["host_entries_max"] == 2
        # Untiered paged log: page ledger narrated, no host-tier keys.
        events2 = _clean_events()
        events2[0] = dict(events2[0], kv_pages=8, prefix_sharing=True)
        for ev in events2:
            if ev["kind"] == "round":
                ev.update(pages_used=4, pages_free=4, pages_aliased=0,
                          page_fragmentation=0.0)
        report2 = rr.build_report(rr.load_runlog(_write(tmp_path,
                                                        events2)))
        kp2 = report2["rounds"]["kv_pages"]
        assert "pages_used_max" in kp2
        assert "spills_total" not in kp2
        assert "host_bytes_max" not in kp2

    def test_restore_round_is_not_a_stall(self, rr, tmp_path):
        # A round that admits nothing while ready work waits is legal
        # when its admission slot went to a host-tier RESTORE — the
        # scheduler was scattering a spilled prefix back into pages,
        # not sitting idle (ISSUE 16). The identical pair with
        # restores == 0 stays a provable queue_stall: the tier must not
        # blind the detector.
        stall_pair = [
            {"kind": "round", "t": 0.105, "round": 2, "iters": 4,
             "occupied": 1, "live_iters": 4, "admitted": 0,
             "retired": 0, "expired": 0, "prefilling": 0,
             "queue_depth": 3, "wasted_row_iters": 4,
             "pages_used": 2, "pages_free": 6, "pages_aliased": 0,
             "page_fragmentation": 0.0, "spills": 0, "restores": 0,
             "host_bytes": 4096, "host_entries": 1},
            {"kind": "round", "t": 0.107, "round": 3, "iters": 4,
             "occupied": 1, "live_iters": 4, "admitted": 0,
             "retired": 0, "expired": 0, "prefilling": 0,
             "queue_depth": 3, "wasted_row_iters": 4,
             "pages_used": 4, "pages_free": 4, "pages_aliased": 0,
             "page_fragmentation": 0.0, "spills": 0, "restores": 1,
             "host_bytes": 4096, "host_entries": 1},
        ]
        events = _clean_events()
        events[0] = dict(events[0], kv_pages=8, prefix_sharing=True,
                         host_kv_bytes=1 << 20)
        events[-1:-1] = stall_pair
        report = rr.build_report(rr.load_runlog(_write(tmp_path, events)))
        assert not [a for a in report["anomalies"]
                    if a["kind"] == "queue_stall"], report["anomalies"]
        # Same pair, no restore: the stall is real.
        events2 = _clean_events()
        events2[0] = dict(events2[0], kv_pages=8, prefix_sharing=True,
                          host_kv_bytes=1 << 20)
        events2[-1:-1] = [dict(stall_pair[0]),
                          dict(stall_pair[1], restores=0)]
        report2 = rr.build_report(rr.load_runlog(_write(tmp_path,
                                                        events2)))
        assert [a for a in report2["anomalies"]
                if a["kind"] == "queue_stall"], report2["anomalies"]

    def test_preemption_rounds_are_narrated(self, rr, tmp_path):
        # Scheduler preemption (ISSUE 17, docs/serving.md §8): preempt/
        # resume events carry the freeze/thaw ledger — the report
        # totals them, names the frozen requests, and keeps the
        # frozen-residency and payload watermarks. Preemption is
        # POLICY: a clean preempting log reports ok. A scheduler-free
        # log must NOT grow the block.
        events = _clean_events()
        events[0] = dict(events[0], kv_pages=8, sched=True)
        events[-1:-1] = [
            {"kind": "preempt", "t": 0.051, "request_id": 1, "row": 1,
             "round": 1, "filled": 28, "pages": 2, "bytes": 8192,
             "spill_s": 0.002},
            {"kind": "round", "t": 0.06, "round": 2, "iters": 4,
             "occupied": 2, "live_iters": 8, "admitted": 1,
             "retired": 0, "expired": 0, "prefilling": 0,
             "queue_depth": 1, "wasted_row_iters": 0,
             "preempts": 1, "resumes": 0, "host_row_bytes": 8192},
            {"kind": "resume", "t": 0.07, "request_id": 1, "row": 0,
             "round": 4, "filled": 28, "pages": 2, "bytes": 8192,
             "frozen_rounds": 3, "restore_s": 0.001},
            {"kind": "round", "t": 0.08, "round": 4, "iters": 4,
             "occupied": 2, "live_iters": 8, "admitted": 0,
             "retired": 0, "expired": 0, "prefilling": 0,
             "queue_depth": 0, "wasted_row_iters": 0,
             "preempts": 0, "resumes": 1, "host_row_bytes": 0},
        ]
        report = rr.build_report(rr.load_runlog(_write(tmp_path, events)))
        assert report["ok"] is True, report["anomalies"]
        pre = report["rounds"]["preemption"]
        assert pre["preempts_total"] == 1
        assert pre["resumes_total"] == 1
        assert pre["preempted_requests"] == [1]
        assert pre["frozen_bytes_max"] == 8192
        assert pre["host_row_bytes_max"] == 8192
        assert pre["frozen_rounds_max"] == 3
        assert pre["spill_s_max"] == 0.002
        assert pre["restore_s_max"] == 0.001
        assert str(pre["preempted_requests"]) in rr._human(report)
        # A scheduler-free log: no preemption block at all.
        report2 = rr.build_report(rr.load_runlog(
            _write(tmp_path, _clean_events())))
        assert "preemption" not in report2["rounds"]

    def test_preempt_round_is_not_a_stall(self, rr, tmp_path):
        # A round that admits nothing while ready work waits is legal
        # when its admission slot went to a FREEZE or a THAW — the
        # engine was moving KV state for the scheduler's priority
        # decision, not sitting idle (ISSUE 17, the restore-round rule
        # one subsystem up). The identical pair with zero freeze/thaw
        # deltas stays a provable queue_stall.
        stall_pair = [
            {"kind": "round", "t": 0.105, "round": 2, "iters": 4,
             "occupied": 1, "live_iters": 4, "admitted": 0,
             "retired": 0, "expired": 0, "prefilling": 0,
             "queue_depth": 3, "wasted_row_iters": 4,
             "preempts": 0, "resumes": 0},
            {"kind": "round", "t": 0.107, "round": 3, "iters": 4,
             "occupied": 1, "live_iters": 4, "admitted": 0,
             "retired": 0, "expired": 0, "prefilling": 0,
             "queue_depth": 3, "wasted_row_iters": 4,
             "preempts": 1, "resumes": 0},
        ]
        for exempt_field in ("preempts", "resumes"):
            events = _clean_events()
            pair = [dict(stall_pair[0]),
                    dict(stall_pair[1], preempts=0, resumes=0)]
            pair[1][exempt_field] = 1
            events[-1:-1] = pair
            report = rr.build_report(
                rr.load_runlog(_write(tmp_path, events)))
            assert not [a for a in report["anomalies"]
                        if a["kind"] == "queue_stall"], \
                (exempt_field, report["anomalies"])
        # Same pair, no freeze/thaw: the stall is real.
        events2 = _clean_events()
        events2[-1:-1] = [dict(stall_pair[0]),
                          dict(stall_pair[1], preempts=0)]
        report2 = rr.build_report(rr.load_runlog(_write(tmp_path,
                                                        events2)))
        assert [a for a in report2["anomalies"]
                if a["kind"] == "queue_stall"], report2["anomalies"]

    def test_spec_rounds_narrated_and_low_acceptance_is_legal(
            self, rr, tmp_path):
        # Speculative rounds (docs/serving.md §7) carry the
        # draft/verify ledger: the report narrates totals, the
        # acceptance trajectory, and the draft lengths the adaptive
        # policy ran. A ZERO-acceptance round is legal steady state —
        # the drafter guessed badly, the verify pass still emitted one
        # token per live row — so it must never be flagged.
        events = _clean_events()
        for ev in events:
            if ev["kind"] != "round":
                continue
            if ev["round"] == 0:
                ev.update(draft_len=4, spec_drafted=24,
                          spec_accepted=12, accept_rate=0.5)
            else:
                ev.update(draft_len=6, spec_drafted=30,
                          spec_accepted=0, accept_rate=0.0)
        report = rr.build_report(rr.load_runlog(_write(tmp_path, events)))
        assert report["ok"] is True, report["anomalies"]
        sp = report["rounds"]["speculative"]
        assert sp["n_spec_rounds"] == 2
        assert sp["drafted_total"] == 54
        assert sp["accepted_total"] == 12
        assert sp["accept_rate_overall"] == pytest.approx(12 / 54,
                                                          abs=1e-4)
        assert sp["accept_rate_mean"] == pytest.approx(0.25)
        assert sp["accept_rate_min"] == 0.0
        assert sp["accept_rate_last"] == 0.0
        assert sp["draft_lens"] == [4, 6]
        assert sp["draft_len_last"] == 6
        assert "speculative: 2 spec round(s)" in rr._human(report)

    def test_genuine_stall_in_spec_log_is_still_flagged(self, rr,
                                                        tmp_path):
        # The other direction: low acceptance must not blind the stall
        # detector — a round pair that sits on ready work with free
        # rows inside a spec log is still a queue_stall.
        events = _clean_events()
        stall_pair = [
            {"kind": "round", "t": 0.105, "round": 2, "iters": 4,
             "occupied": 1, "live_iters": 4, "admitted": 0,
             "retired": 0, "expired": 0, "prefilling": 0,
             "queue_depth": 3, "wasted_row_iters": 4,
             "draft_len": 4, "spec_drafted": 12, "spec_accepted": 0,
             "accept_rate": 0.0},
            {"kind": "round", "t": 0.107, "round": 3, "iters": 4,
             "occupied": 1, "live_iters": 4, "admitted": 0,
             "retired": 0, "expired": 0, "prefilling": 0,
             "queue_depth": 3, "wasted_row_iters": 4,
             "draft_len": 4, "spec_drafted": 12, "spec_accepted": 0,
             "accept_rate": 0.0},
        ]
        events[-1:-1] = stall_pair
        report = rr.build_report(rr.load_runlog(_write(tmp_path, events)))
        assert [a for a in report["anomalies"]
                if a["kind"] == "queue_stall"], report["anomalies"]

    def test_unresolved_request_only_in_sealed_logs(self, rr, tmp_path):
        events = _clean_events()
        orphan = {"kind": "submit", "t": 0.012, "request_id": 9,
                  "prompt_len": 8, "steps": 4, "round": 0,
                  "queue_depth": 3}
        events.insert(3, orphan)
        report = rr.build_report(rr.load_runlog(_write(tmp_path, events)))
        assert [a["kind"] for a in report["anomalies"]] \
            == ["unresolved_request"]
        # The same orphan in an UNSEALED log (mid-flight snapshot) is
        # not an anomaly — the request may simply still be running.
        unsealed = [e for e in events if e["kind"] != "drain_complete"]
        report2 = rr.build_report(
            rr.load_runlog(_write(tmp_path, unsealed, "u.jsonl")))
        assert report2["ok"] is True

    def test_cli_exit_codes(self, rr, tmp_path, capsys):
        clean = _write(tmp_path, _clean_events())
        assert rr.main([clean]) == 0
        out = capsys.readouterr().out
        assert "no anomalies" in out and "phase sums: 2 checked" in out
        bad = _clean_events()
        bad.insert(-1, {"kind": "compile", "t": 0.1, "round": 1,
                        "entry": "serving.decode_round",
                        "new_compiles": 1})
        assert rr.main([_write(tmp_path, bad, "bad.jsonl")]) == 1
        capsys.readouterr()
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        assert rr.main([str(empty)]) == 2
        assert rr.main([str(tmp_path / "missing.jsonl")]) == 2
        # --json - emits ONLY the JSON report.
        assert rr.main([clean, "--json", "-"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["ok"] is True

    def test_series_flag_inlines_rounds(self, rr, tmp_path):
        report = rr.build_report(
            rr.load_runlog(_write(tmp_path, _clean_events())),
            series=True)
        assert len(report["round_series"]) == 2
        assert report["round_series"][0]["iters"] == 4


def _stall_pair(matrix_quanta):
    """The provable stall signature (round N ready work + free rows,
    round N+1 does nothing) with ``matrix_quanta`` stamped on the
    would-be stall round."""
    return [
        {"kind": "round", "t": 0.105, "round": 2, "iters": 4,
         "occupied": 1, "live_iters": 4, "admitted": 0, "retired": 0,
         "expired": 0, "prefilling": 0, "queue_depth": 3,
         "wasted_row_iters": 4},
        {"kind": "round", "t": 0.107, "round": 3, "iters": 4,
         "occupied": 1, "live_iters": 4, "admitted": 0, "retired": 0,
         "expired": 0, "prefilling": 0, "queue_depth": 3,
         "wasted_row_iters": 4, "matrix_quanta": matrix_quanta},
        {"kind": "submit", "t": 0.1055, "request_id": 7,
         "prompt_len": 8, "steps": 2, "round": 2, "queue_depth": 4},
        {"kind": "timeout", "t": 0.108, "request_id": 7, "round": 3,
         "deadline_rounds": 0, "wait_s": 0.5},
    ]


def _matrix_job_events():
    """The matrix service's job_* narrative (docs/matrix_service.md)
    grafted onto the clean log: job 0 prices, executes over rounds 0-1,
    and completes; job 1 crashes once mid-quantum, replays from its
    seed, and completes."""
    return [
        {"kind": "job_submit", "t": 0.012, "job_id": 0, "op": "gemm",
         "shapes": [64, 32, 16], "dtype": "float32", "units": 32768.0,
         "n_quanta": 2, "quanta_per_round": 1, "predicted_rounds": 2,
         "predicted_s": 0.002},
        {"kind": "job_phase", "t": 0.04, "job_id": 0,
         "phase": "execute", "quantum": 0, "n_quanta": 2, "round": 0},
        {"kind": "job_phase", "t": 0.09, "job_id": 0,
         "phase": "encode", "quantum": 2, "n_quanta": 2, "round": 1},
        {"kind": "job_complete", "t": 0.095, "job_id": 0, "op": "gemm",
         "status": "done", "quanta": 2, "measured_s": 0.0021,
         "result_bytes": 4242, "predicted_s": 0.002,
         "budget_rel_err": 0.05},
        {"kind": "job_submit", "t": 0.013, "job_id": 1, "op": "lu",
         "shapes": [48], "dtype": "float32", "units": 73728.0,
         "n_quanta": 3, "quanta_per_round": 1, "predicted_rounds": 3},
        {"kind": "job_phase", "t": 0.05, "job_id": 1,
         "phase": "execute", "quantum": 0, "n_quanta": 3, "round": 1},
        {"kind": "job_replay", "t": 0.06, "job_id": 1,
         "crash_count": 1, "error": "FaultInjected: matrix_quantum"},
        {"kind": "job_phase", "t": 0.07, "job_id": 1,
         "phase": "execute", "quantum": 0, "n_quanta": 3, "round": 1},
        {"kind": "job_complete", "t": 0.1, "job_id": 1, "op": "lu",
         "status": "done", "quanta": 3, "measured_s": 0.03,
         "result_bytes": 9000},
    ]


class TestMatrixServiceNarration:
    def test_matrix_quanta_round_is_not_a_stall(self, rr, tmp_path):
        """A round that spent its budget on matrix work quanta was
        executing, not sitting on ready work — exempt from the stall
        detector (the round event carries ``matrix_quanta``)."""
        events = _clean_events()
        events[-1:-1] = _stall_pair(matrix_quanta=3)
        report = rr.build_report(rr.load_runlog(
            _write(tmp_path, events)))
        assert not [a for a in report["anomalies"]
                    if a["kind"] == "queue_stall"], report["anomalies"]

    def test_same_round_without_matrix_quanta_is_a_stall(self, rr,
                                                         tmp_path):
        # Pinned the other way: the identical pair with zero matrix
        # quanta stays a provable queue_stall — the exemption must not
        # swallow genuine stalls in a matrix-enabled log.
        events = _clean_events()
        events[-1:-1] = _stall_pair(matrix_quanta=0)
        report = rr.build_report(rr.load_runlog(
            _write(tmp_path, events)))
        stalls = [a for a in report["anomalies"]
                  if a["kind"] == "queue_stall"]
        assert len(stalls) == 1 and stalls[0]["round"] == 3

    def test_job_timeline_joins_the_job_event_family(self, rr,
                                                     tmp_path):
        events = _clean_events()
        events[-1:-1] = _matrix_job_events()
        report = rr.build_report(rr.load_runlog(
            _write(tmp_path, events)))
        assert report["ok"] is True, report["anomalies"]
        assert report["n_matrix_jobs"] == 2
        assert report["n_matrix_poisoned"] == 0
        j0, j1 = report["matrix_jobs"]
        assert j0["op"] == "gemm" and j0["status"] == "done"
        assert j0["units"] == 32768.0 and j0["n_quanta"] == 2
        assert j0["execute_round"] == 0 and j0["encode_round"] == 1
        assert j0["predicted_s"] == 0.002
        assert j0["budget_rel_err"] == 0.05
        assert j1["op"] == "lu" and j1["status"] == "done"
        assert j1["replays"] == 1
        assert "FaultInjected" in j1["last_error"]

    def test_llm_only_report_has_no_matrix_block(self, rr, tmp_path):
        report = rr.build_report(rr.load_runlog(
            _write(tmp_path, _clean_events())))
        assert "matrix_jobs" not in report
        assert "n_matrix_jobs" not in report

    def test_unresolved_job_in_sealed_log_is_flagged(self, rr,
                                                     tmp_path):
        events = _clean_events()
        events[-1:-1] = _matrix_job_events()[:2]  # submit + execute
        report = rr.build_report(rr.load_runlog(
            _write(tmp_path, events)))
        assert report["ok"] is False
        (a,) = [x for x in report["anomalies"]
                if x["kind"] == "unresolved_matrix_job"]
        assert a["job_id"] == 0

    def test_quarantine_resolves_a_job(self, rr, tmp_path):
        events = _clean_events()
        events[-1:-1] = _matrix_job_events()[:2] + [
            {"kind": "job_replay", "t": 0.05, "job_id": 0,
             "crash_count": 1, "error": "RuntimeError: boom"},
            {"kind": "job_quarantine", "t": 0.06, "job_id": 0,
             "crash_count": 2, "error": "RuntimeError: boom"},
        ]
        report = rr.build_report(rr.load_runlog(
            _write(tmp_path, events)))
        assert not [x for x in report["anomalies"]
                    if x["kind"] == "unresolved_matrix_job"]
        (j,) = report["matrix_jobs"]
        assert j["status"] == "poisoned" and j["crash_count"] == 2
        assert report["n_matrix_poisoned"] == 1


def _crash_cycle_events():
    """A clean crash/recovery narrative grafted onto the clean log:
    round 1 crashes with request 0 in flight and request 1 queued, both
    recover into the successor engine (its own engine_start), and both
    complete post-restart."""
    events = _clean_events()
    # Drop the pre-crash completes; the requests resolve after recovery.
    events = [e for e in events if e["kind"] != "complete"]
    seal = events.pop()  # drain_complete goes back at the end
    events += [
        {"kind": "engine_crash", "t": 0.06, "round": 1,
         "error": "FaultInjected: injected", "error_type": "FaultInjected",
         "blamed_request_id": None, "inflight": [0], "queued": [1],
         "crashes_in_window": 1},
        {"kind": "recover", "t": 0.061, "request_id": 0, "round": 2,
         "crash_count": 1, "requeues": 1, "recovery_s": 0.01},
        {"kind": "recover", "t": 0.062, "request_id": 1, "round": 2,
         "crash_count": 0, "requeues": 1, "recovery_s": 0.0},
        {"kind": "engine_start", "t": 0.063, "batch": 2,
         "round_steps": 4, "prefill_chunk": None, "max_pending": 8,
         "max_len": 64, "prefix_cache": False},
        {"kind": "admit", "t": 0.07, "request_id": 0, "row": 0,
         "round": 2, "prompt_len": 8, "wait_rounds": 2,
         "queue_depth": 1},
        {"kind": "admit", "t": 0.071, "request_id": 1, "row": 1,
         "round": 2, "prompt_len": 24, "wait_rounds": 2,
         "queue_depth": 0},
        {"kind": "round", "t": 0.08, "round": 2, "iters": 4,
         "occupied": 2, "live_iters": 8, "admitted": 2, "retired": 2,
         "expired": 0, "prefilling": 0, "queue_depth": 0,
         "wasted_row_iters": 0, "round_s": 0.02, "decode_s": 0.018,
         "drift_decode": 1.0},
        {"kind": "complete", "t": 0.09, "request_id": 0, "row": 0,
         "emitted": 4, "live_iters": 4, "submit_t": 1.00,
         "admit_t": 1.07, "finish_t": 1.09, "rounds": 1,
         "phases": {"queue_wait": 0.06, "admit": 0.01,
                    "decode": 0.02, "total": 0.09, "recovery": 0.05}},
        {"kind": "complete", "t": 0.095, "request_id": 1, "row": 1,
         "emitted": 4, "live_iters": 4, "submit_t": 1.001,
         "admit_t": 1.072, "finish_t": 1.094, "rounds": 1,
         "phases": {"queue_wait": 0.069, "admit": 0.004,
                    "decode": 0.02, "total": 0.093}},
        seal,
    ]
    return events


class TestCrashCycleDetector:
    """PR-7 (docs/robustness.md): every request a crash interrupts must
    resolve — recovered or quarantined, never silently lost — and the
    report narrates the cycle without treating a RESOLVED chaos run as
    an anomaly."""

    def test_resolved_crash_cycle_is_clean_and_reported(self, rr,
                                                        tmp_path):
        report = rr.build_report(rr.load_runlog(
            _write(tmp_path, _crash_cycle_events())))
        assert report["ok"] is True, report["anomalies"]
        assert report["n_crashes"] == 1
        assert report["n_recovered"] == 2
        assert report["n_quarantined"] == 0
        assert report["engine_failed"] is False
        (cycle,) = report["crashes"]
        assert cycle["interrupted"] == [0, 1]
        assert sorted(cycle["recovered"]) == [0, 1]
        # The recovery sub-attribution rides OUTSIDE the contiguous
        # sum: phase checks still pass on the recovered request.
        r0 = next(r for r in report["requests"] if r["request_id"] == 0)
        assert r0["recoveries"] == 1
        assert r0["phase_sum_rel_err"] <= 0.05

    def test_crashed_request_vanishing_is_flagged(self, rr, tmp_path):
        events = [e for e in _crash_cycle_events()
                  if not (e["kind"] == "recover"
                          and e["request_id"] == 1)
                  and not (e["kind"] == "complete"
                           and e["request_id"] == 1)
                  and not (e["kind"] == "admit"
                           and e["request_id"] == 1)]
        report = rr.build_report(rr.load_runlog(_write(tmp_path, events)))
        assert report["ok"] is False
        kinds = sorted(a["kind"] for a in report["anomalies"])
        assert "crash_unresolved_request" in kinds
        a = next(a for a in report["anomalies"]
                 if a["kind"] == "crash_unresolved_request")
        assert a["request_id"] == 1
        # ... and the sealed log also flags it as unresolved overall.
        assert "unresolved_request" in kinds

    def test_quarantine_resolves_the_cycle(self, rr, tmp_path):
        events = _crash_cycle_events()
        # Request 1 is quarantined instead of recovered.
        for i, e in enumerate(events):
            if e["kind"] == "recover" and e["request_id"] == 1:
                events[i] = {"kind": "quarantine", "t": e["t"],
                             "request_id": 1, "crash_count": 2,
                             "error": "FaultInjected: injected"}
        events = [e for e in events
                  if not (e["kind"] in ("admit", "complete")
                          and e.get("request_id") == 1)]
        report = rr.build_report(rr.load_runlog(_write(tmp_path, events)))
        assert report["ok"] is True, report["anomalies"]
        assert report["n_quarantined"] == 1
        (cycle,) = report["crashes"]
        assert cycle["quarantined"] == [1]
        r1 = next(r for r in report["requests"] if r["request_id"] == 1)
        assert r1["status"] == "poisoned"

    def test_engine_failed_resolves_named_abandoned(self, rr, tmp_path):
        events = _crash_cycle_events()
        # Second crash whose requests are abandoned by fail-closed;
        # the log is NOT sealed (a failed engine never drains).
        events = [e for e in events if e["kind"] != "drain_complete"]
        events += [
            {"kind": "engine_crash", "t": 0.12, "round": 3,
             "error": "FaultInjected: injected",
             "error_type": "FaultInjected", "blamed_request_id": None,
             "inflight": [2], "queued": [], "crashes_in_window": 2},
            {"kind": "engine_failed", "t": 0.121, "round": 3,
             "restarts": 1, "abandoned": [2],
             "error": "FaultInjected: injected"},
        ]
        report = rr.build_report(rr.load_runlog(_write(tmp_path, events)))
        assert report["engine_failed"] is True
        assert not any(a["kind"] == "crash_unresolved_request"
                       for a in report["anomalies"]), report["anomalies"]


class TestRealEngineRunlog:
    def test_engine_drain_runlog_is_clean(self, rr, tmp_path):
        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=64)
        params = init_params(cfg, seed=0)
        path = tmp_path / "engine_runlog.jsonl"
        eng = ServingEngine(params, cfg, batch=2, round_steps=4,
                            runlog=RunLog(maxlen=8, path=path))
        rng = np.random.default_rng(3)
        for i in range(5):
            eng.submit(rng.integers(0, cfg.vocab, 8), int(2 + i))
        done = eng.drain()
        assert len(done) == 5
        report = rr.build_report(rr.load_runlog(str(path)))
        assert report["ok"] is True, report["anomalies"]
        assert report["sealed"] is True
        assert report["n_completed"] == 5
        assert report["post_warmup_compiles"] == 0
        assert report["phase_sum_checked"] == 5
        # The identity: contiguous stamps on one clock; 6-decimal
        # runlog rounding is the only slack the analyzer needs.
        assert report["phase_sum_max_rel_err"] <= 0.01
        assert report["rounds"]["n_rounds"] == eng.stats.n_rounds
        assert report["rounds"]["batch"] == 2
        assert report["ledger"] == eng.stats.summary()


# -- fleet merge (docs/fleet.md §observability) ------------------------


def _remap_ids(events, mapping):
    out = []
    for ev in events:
        ev = dict(ev)
        if "request_id" in ev:
            ev["request_id"] = mapping.get(ev["request_id"],
                                           ev["request_id"])
        out.append(ev)
    return out


def _router_events():
    return [
        {"kind": "fleet_route", "t": 0.0, "request_id": 0,
         "replica": 0, "policy": "fallback", "hit_depth": 0},
        {"kind": "fleet_route", "t": 0.01, "request_id": 1,
         "replica": 0, "policy": "affinity", "hit_depth": 16},
        {"kind": "fleet_route", "t": 0.02, "request_id": 2,
         "replica": 1, "policy": "fallback", "hit_depth": 0},
        {"kind": "fleet_route", "t": 0.03, "request_id": 3,
         "replica": 1, "policy": "affinity", "hit_depth": 16},
    ]


class TestFleetMerge:
    def _entries(self, rr, paths):
        entries = []
        for p in paths:
            replica, inc = rr.classify_runlog(p)
            entries.append({"path": p, "replica": replica,
                            "incarnation": inc,
                            "events": rr.load_runlog(p)})
        return entries

    def test_classify_runlog_filenames(self, rr):
        assert rr.classify_runlog("/x/replica0.jsonl") == (0, 0)
        assert rr.classify_runlog("runlogs/replica3.r2.jsonl") == (3, 2)
        assert rr.classify_runlog("router.jsonl") == (None, None)
        assert rr.classify_runlog("engine.jsonl") == (None, None)

    def test_clean_fleet_merges_by_replica(self, rr, tmp_path):
        """Two clean replicas + the router log: per-replica summaries,
        router route/policy counts, all request ids unique, ok."""
        paths = [
            _write(tmp_path, _clean_events(), "replica0.jsonl"),
            _write(tmp_path, _remap_ids(_clean_events(), {0: 2, 1: 3}),
                   "replica1.jsonl"),
            _write(tmp_path, _router_events(), "router.jsonl"),
        ]
        report = rr.build_fleet_report(self._entries(rr, paths))
        assert report["ok"] is True, report["anomalies"]
        assert report["n_replicas"] == 2 and report["n_files"] == 3
        for key in ("0", "1"):
            e = report["replicas"][key]
            assert e["n_incarnations"] == 1
            assert e["n_submitted"] == e["n_completed"] == 2
            assert e["busy_s"] == pytest.approx(0.09)
            assert e["incarnations"][0]["sealed"] is True
        assert report["n_unique_request_ids"] == 4
        assert report["n_replayed_after_abandonment"] == 0
        assert report["router"]["n_routes"] == 4
        assert report["router"]["routes_by_policy"] == {
            "affinity": 2, "fallback": 2}
        assert report["router"]["n_failovers"] == 0

    def test_incarnations_fold_into_one_replica(self, rr, tmp_path):
        """replica0.jsonl + replica0.r1.jsonl = ONE replica, two
        incarnation timelines, each analyzed separately (the respawn
        gets a fresh engine timeline by design)."""
        paths = [
            _write(tmp_path, _clean_events(), "replica0.jsonl"),
            _write(tmp_path, _remap_ids(_clean_events(), {0: 4, 1: 5}),
                   "replica0.r1.jsonl"),
        ]
        report = rr.build_fleet_report(self._entries(rr, paths))
        assert report["ok"] is True, report["anomalies"]
        assert report["n_replicas"] == 1
        e = report["replicas"]["0"]
        assert e["n_incarnations"] == 2
        assert [i["incarnation"] for i in e["incarnations"]] == [0, 1]
        assert e["n_completed"] == 4
        assert e["busy_s"] == pytest.approx(0.18)

    def test_replay_after_abandonment_is_legitimate(self, rr,
                                                    tmp_path):
        """rid 10 submitted on replica 0, abandoned at engine_failed
        (fail-closed), then replayed and completed on replica 1: NOT a
        duplicate — the exact shape the router's failover produces."""
        failed = [
            {"kind": "engine_start", "t": 0.0, "batch": 2,
             "round_steps": 4, "max_pending": 8, "max_len": 64},
            {"kind": "submit", "t": 0.01, "request_id": 10,
             "prompt_len": 8, "steps": 4, "round": 0,
             "queue_depth": 1},
            {"kind": "engine_failed", "t": 0.02, "round": 0,
             "abandoned": [10], "error_type": "FaultInjected"},
        ]
        peer = _remap_ids(_clean_events(), {0: 10, 1: 11})
        paths = [
            _write(tmp_path, failed, "replica0.jsonl"),
            _write(tmp_path, peer, "replica1.jsonl"),
        ]
        report = rr.build_fleet_report(self._entries(rr, paths))
        assert report["ok"] is True, report["anomalies"]
        assert report["n_replayed_after_abandonment"] == 1
        assert report["n_unique_request_ids"] == 2
        assert report["replicas"]["0"]["incarnations"][0][
            "engine_failed"] is True

    def test_live_duplicate_rid_is_an_anomaly(self, rr, tmp_path):
        """The same rid live (not abandoned) on two replicas breaks
        the router's global-uniqueness contract — and with it the
        byte-exactness doctrine, since two engines folded the same id
        into their streams."""
        paths = [
            _write(tmp_path, _clean_events(), "replica0.jsonl"),
            _write(tmp_path, _clean_events(), "replica1.jsonl"),
        ]
        report = rr.build_fleet_report(self._entries(rr, paths))
        assert report["ok"] is False
        dups = [a for a in report["anomalies"]
                if a["kind"] == "duplicate_request_id"]
        assert sorted(a["request_id"] for a in dups) == [0, 1]
        apps = dups[0]["appearances"]
        assert {a["replica"] for a in apps} == {"0", "1"}

    def test_per_replica_anomalies_carry_the_replica_key(self, rr,
                                                         tmp_path):
        """A single-log anomaly (steady-state compile) surfaces in the
        merged report tagged with its replica/incarnation."""
        bad = _clean_events()
        bad.insert(-1, {"kind": "compile", "t": 0.098, "round": 1,
                        "entry": "serving.decode_round",
                        "new_compiles": 1})
        paths = [
            _write(tmp_path, bad, "replica0.r1.jsonl"),
            _write(tmp_path, _remap_ids(_clean_events(), {0: 2, 1: 3}),
                   "replica1.jsonl"),
        ]
        report = rr.build_fleet_report(self._entries(rr, paths))
        assert report["ok"] is False
        a = next(a for a in report["anomalies"]
                 if a["kind"] == "post_warmup_compile")
        assert a["replica"] == "0" and a["incarnation"] == 1

    def test_cli_fleet_merge_and_exit_codes(self, rr, tmp_path,
                                            capsys):
        paths = [
            _write(tmp_path, _clean_events(), "replica0.jsonl"),
            _write(tmp_path, _remap_ids(_clean_events(), {0: 2, 1: 3}),
                   "replica1.jsonl"),
            _write(tmp_path, _router_events(), "router.jsonl"),
        ]
        assert rr.main(paths + ["--json", "-"]) == 0
        report = json.loads(capsys.readouterr().out)
        assert report["fleet"] is True and report["ok"] is True
        # Human form names each replica and the router.
        assert rr.main(paths) == 0
        out = capsys.readouterr().out
        assert "replica 0:" in out and "replica 1:" in out
        assert "router: 4 route(s)" in out
        # Duplicate ids -> exit 1.
        dup = [_write(tmp_path, _clean_events(), "replica2.jsonl"),
               _write(tmp_path, _clean_events(), "replica3.jsonl")]
        assert rr.main(dup + ["--json", str(tmp_path / "r.json")]) == 1
        capsys.readouterr()  # drain the dup run's human summary
        # Single path keeps the original single-log behavior.
        assert rr.main([paths[0], "--json", "-"]) == 0
        single = json.loads(capsys.readouterr().out)
        assert "fleet" not in single and single["n_completed"] == 2
