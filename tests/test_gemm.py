"""GEMM dispatch-arm tests — every arm, like the reference suite
(DistributedMatrixSuite.scala:225-434 covers broadcast, explicit (m,k,n) splits
incl. k=1, local-matrix broadcast, mixed DenseVec x Block, Block x DenseVec,
Block x Block, broadcast B)."""

import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu.matrix.block import BlockMatrix
from marlin_tpu.matrix.dense import DenseVecMatrix
from marlin_tpu.matrix.vector import DistributedVector
from marlin_tpu.parallel import summa
from marlin_tpu.utils import random as mrand


@pytest.fixture(scope="module")
def abn():
    rng = np.random.default_rng(1742)
    a = rng.standard_normal((23, 17))
    b = rng.standard_normal((17, 29))
    return a, b


class TestDenseVecMultiply:
    def test_broadcast_arm(self, abn):
        a, b = abn
        c = DenseVecMatrix(a).multiply(DenseVecMatrix(b))  # auto: B is tiny
        assert isinstance(c, DenseVecMatrix)
        np.testing.assert_allclose(c.to_numpy(), a @ b, rtol=1e-12)

    def test_local_matrix_broadcast(self, abn):
        a, b = abn
        c = DenseVecMatrix(a).multiply(b)  # raw ndarray operand
        np.testing.assert_allclose(c.to_numpy(), a @ b, rtol=1e-12)

    def test_left_broadcast_arm(self, abn):
        a, b = abn
        # Force the mirrored Branch B: self (3128 B) under threshold, other
        # (3944 B) over it.
        assert a.nbytes < 3500 < b.nbytes
        c = DenseVecMatrix(a).multiply(
            DenseVecMatrix(b), broadcast_threshold_mb=3500 / 1e6
        )
        np.testing.assert_allclose(c.to_numpy(), a @ b, rtol=1e-12)

    def test_split_path_when_both_large(self, abn):
        a, b = abn
        # Both over threshold -> near-square SUMMA split path.
        c = DenseVecMatrix(a).multiply(DenseVecMatrix(b), broadcast_threshold_mb=1e-9)
        assert isinstance(c, BlockMatrix)
        np.testing.assert_allclose(c.to_numpy(), a @ b, rtol=1e-12)

    def test_carma_branch_d(self, rng):
        # Non-near-square shapes with both operands over threshold -> Branch D
        # (CARMA grid). m >> k, n: grid (8,1,1) -> k-degenerate 2-D engine.
        a = rng.standard_normal((640, 8))
        b = rng.standard_normal((8, 16))
        c = DenseVecMatrix(a).multiply(DenseVecMatrix(b), broadcast_threshold_mb=1e-9)
        np.testing.assert_allclose(c.to_numpy(), a @ b, rtol=1e-12, atol=1e-13)

    def test_carma_branch_d_k_split(self, rng):
        # k >> m, n: the CARMA grid splits k -> the 3-D psum engine.
        from marlin_tpu.utils.split import grid_for_devices

        a = rng.standard_normal((8, 640))
        b = rng.standard_normal((640, 8))
        grid = grid_for_devices(8, 640, 8, 8)
        assert grid[1] > 1  # policy must give the k axis the budget
        c = DenseVecMatrix(a).multiply(DenseVecMatrix(b), broadcast_threshold_mb=1e-9)
        np.testing.assert_allclose(c.to_numpy(), a @ b, rtol=1e-11, atol=1e-12)

    def test_local_vector_operand(self, abn):
        a, _ = abn
        x = np.arange(17.0)
        y = DenseVecMatrix(a).multiply(x)
        np.testing.assert_allclose(y.to_numpy(), a @ x, rtol=1e-12)

    @pytest.mark.parametrize("engine", ["summa", "gspmd"])
    def test_split_engines(self, abn, engine):
        a, b = abn
        c = DenseVecMatrix(a).multiply(DenseVecMatrix(b), mode=engine)
        assert isinstance(c, BlockMatrix)
        np.testing.assert_allclose(c.to_numpy(), a @ b, rtol=1e-12)

    @pytest.mark.parametrize(
        "grid", [(2, 2, 2), (8, 1, 1), (1, 8, 1), (1, 1, 8), (4, 2, 1), (2, 1, 4)]
    )
    def test_explicit_mkn_splits(self, abn, grid):
        # The multiply(that, (m,k,n)) overload incl. k=1 (suite :236).
        a, b = abn
        c = DenseVecMatrix(a).multiply(DenseVecMatrix(b), mode=grid)
        np.testing.assert_allclose(c.to_numpy(), a @ b, rtol=1e-12)

    def test_forced_grid_fallback_is_loud(self, abn):
        # A forced (m,k,n) the mesh can't place must not reroute SILENTLY
        # (VERDICT r02 weak-5; the reference treats the explicit split as a
        # command, DenseVecMatrix.scala:109): the metrics registry counts
        # the fallback and the caller gets a warning.
        from marlin_tpu.utils.timing import metrics

        a, b = abn
        before = metrics.counters["gemm.grid_fallback"]
        with pytest.warns(UserWarning, match="2-D engine"):
            c = DenseVecMatrix(a).multiply(DenseVecMatrix(b), mode=(4, 4, 4))
        assert metrics.counters["gemm.grid_fallback"] == before + 1
        np.testing.assert_allclose(c.to_numpy(), a @ b, rtol=1e-12)

    def test_auto_grid_fallback_no_warning(self, abn, recwarn):
        # The auto-dispatch arm may legitimately route a degenerate grid to
        # the 2-D engine without warning the caller (it wasn't a command).
        a, b = abn
        DenseVecMatrix(a)._multiply_grid(
            DenseVecMatrix(b), (4, 4, 4), forced=False)
        assert not [w for w in recwarn.list
                    if "2-D engine" in str(w.message)]

    def test_cannon_square_mesh(self, abn):
        a, b = abn
        import jax

        mesh = mt.create_mesh((2, 2), devices=jax.devices()[:4])
        out = summa.matmul(
            mt.DenseVecMatrix(a, mesh=mesh).logical,
            mt.DenseVecMatrix(b, mesh=mesh).logical,
            mesh=mesh,
            engine="cannon",
        )
        np.testing.assert_allclose(np.asarray(out), a @ b, rtol=1e-12)

    def test_dimension_mismatch(self, abn):
        a, b = abn
        with pytest.raises(ValueError):
            DenseVecMatrix(a).multiply(DenseVecMatrix(a))

    def test_matvec(self, abn):
        a, _ = abn
        x = np.arange(1.0, 18.0)
        y = DenseVecMatrix(a).multiply(DistributedVector(x))
        np.testing.assert_allclose(y.to_numpy(), a @ x, rtol=1e-12)


class TestBlockMultiply:
    def test_block_x_block(self, abn):
        a, b = abn
        c = BlockMatrix(a).multiply(BlockMatrix(b), mode="summa")
        np.testing.assert_allclose(c.to_numpy(), a @ b, rtol=1e-12)

    def test_block_x_block_regrid(self, abn):
        # Mismatched logical grids (suite :420) — grids are metadata here.
        a, b = abn
        am = BlockMatrix(a, blks_by_row=4, blks_by_col=2)
        bm = BlockMatrix(b, blks_by_row=3, blks_by_col=3)
        np.testing.assert_allclose(
            am.multiply(bm, mode="summa").to_numpy(), a @ b, rtol=1e-12
        )

    def test_block_broadcast_b(self, abn):
        a, b = abn
        c = BlockMatrix(a).multiply(BlockMatrix(b))  # auto: under threshold
        np.testing.assert_allclose(c.to_numpy(), a @ b, rtol=1e-12)

    def test_block_x_local_and_vector(self, abn):
        a, b = abn
        np.testing.assert_allclose(
            BlockMatrix(a).multiply(b).to_numpy(), a @ b, rtol=1e-12
        )
        x = np.ones(17)
        y = BlockMatrix(a).multiply(x)
        np.testing.assert_allclose(y.to_numpy(), a @ x, rtol=1e-12)

    def test_multiply_by_left(self, abn):
        a, b = abn
        np.testing.assert_allclose(
            BlockMatrix(b).multiply_by(a).to_numpy(), a @ b, rtol=1e-12
        )

    def test_mixed_dense_block(self, abn):
        a, b = abn
        c = DenseVecMatrix(a).multiply(BlockMatrix(b), mode="summa")
        np.testing.assert_allclose(c.to_numpy(), a @ b, rtol=1e-12)
        c2 = BlockMatrix(a).multiply(DenseVecMatrix(b), mode="summa")
        np.testing.assert_allclose(c2.to_numpy(), a @ b, rtol=1e-12)

    def test_scalar(self, abn):
        a, _ = abn
        np.testing.assert_allclose(BlockMatrix(a).multiply(2.0).to_numpy(), a * 2)


class TestEngines3D:
    def test_matmul_3d_uneven_shapes(self, rng):
        a = rng.standard_normal((13, 11))
        b = rng.standard_normal((11, 9))
        out = summa.matmul_3d(a, b, (2, 2, 2))
        np.testing.assert_allclose(np.asarray(out), a @ b, rtol=1e-12)

    def test_grid_for_devices_covers(self):
        from marlin_tpu.utils.split import grid_for_devices

        for m, k, n in [(1000, 10, 10), (10, 1000, 10), (128, 128, 128)]:
            pm, pk, pn = grid_for_devices(m, k, n, 8)
            assert pm * pk * pn == 8

    def test_split_method_policy(self):
        from marlin_tpu.utils.split import split_method

        ms, ks, ns = split_method(1 << 20, 4, 4, 8)
        assert ms == 8 and ks == 1 and ns == 1  # all budget to the huge dim
        ms, ks, ns = split_method(64, 64, 64, 8)
        assert ms * ks * ns <= 8


class TestGramian:
    def test_compute_gramian(self, abn):
        a, _ = abn
        g = DenseVecMatrix(a).compute_gramian_matrix()
        np.testing.assert_allclose(g, a.T @ a, rtol=1e-12)

    def test_gramian_matvec(self, abn):
        a, _ = abn
        v = np.linspace(-1, 1, 17)
        out = DenseVecMatrix(a).multiply_gramian_matrix_by(v)
        np.testing.assert_allclose(out, a.T @ (a @ v), rtol=1e-12)


class TestRandomGeneration:
    def test_deterministic_and_sharded(self):
        m1 = mrand.random_den_vec_matrix(32, 16, seed=7)
        m2 = mrand.random_den_vec_matrix(32, 16, seed=7)
        np.testing.assert_array_equal(m1.to_numpy(), m2.to_numpy())
        assert not np.allclose(
            m1.to_numpy(), mrand.random_den_vec_matrix(32, 16, seed=8).to_numpy()
        )

    def test_distributions(self):
        n = mrand.random_den_vec_matrix(200, 100, distribution="normal", seed=1)
        assert abs(n.to_numpy().mean()) < 0.05
        u = mrand.random_block_matrix(64, 64, distribution="uniform", seed=2)
        arr = u.to_numpy()
        assert 0 <= arr.min() and arr.max() <= 1
        z = mrand.zeros_den_vec_matrix(8, 8)
        assert z.sum() == 0
        o = mrand.ones_den_vec_matrix(8, 8)
        assert o.sum() == 64
        p = mrand.random_den_vec_matrix(
            100, 100, distribution="poisson", seed=3, mean=4.0
        )
        assert abs(p.to_numpy().mean() - 4.0) < 0.2

    def test_vector_factories(self):
        v = mrand.random_dist_vector(100, seed=5)
        assert v.length == 100
        assert mrand.ones_dist_vector(10).to_numpy().sum() == 10

    def test_sparse_generation(self):
        sp = mrand.random_spa_vec_matrix(100, 100, sparsity=0.1, seed=6)
        dens = (sp.to_numpy() != 0).mean()
        assert 0.05 < dens < 0.15


class TestParallelismHint:
    """The reference's `cores` argument caps partitions on EVERY dispatch arm
    (DenseVecMatrix.scala:196-231); here it routes through a submesh."""

    def test_dense_all_arms_honor_hint(self, rng):
        a = DenseVecMatrix(rng.standard_normal((48, 40)))
        b = DenseVecMatrix(rng.standard_normal((40, 32)))
        oracle = a.to_numpy() @ b.to_numpy()
        for mode in (None, "summa", "gspmd", "broadcast"):
            out = a.multiply(b, parallelism=2, mode=mode)
            assert len(out.data.sharding.device_set) == 2, mode
            np.testing.assert_allclose(out.to_numpy(), oracle, rtol=1e-10)

    def test_dense_auto_big_vs_small_threshold(self, rng):
        # Force the non-broadcast arm with a tiny threshold: the submesh must
        # carry the SUMMA path too.
        a = DenseVecMatrix(rng.standard_normal((64, 64)))
        b = DenseVecMatrix(rng.standard_normal((64, 64)))
        out = a.multiply(b, parallelism=4, broadcast_threshold_mb=1e-9)
        assert len(out.data.sharding.device_set) == 4
        np.testing.assert_allclose(
            out.to_numpy(), a.to_numpy() @ b.to_numpy(), rtol=1e-10
        )

    def test_block_honors_hint(self, rng):
        a = BlockMatrix(rng.standard_normal((32, 24)))
        b = BlockMatrix(rng.standard_normal((24, 16)))
        out = a.multiply(b, parallelism=2, broadcast_threshold_mb=1e-9)
        assert len(out.data.sharding.device_set) == 2
        np.testing.assert_allclose(
            out.to_numpy(), a.to_numpy() @ b.to_numpy(), rtol=1e-10
        )

    def test_hint_capped_at_device_count(self, rng):
        a = DenseVecMatrix(rng.standard_normal((16, 8)))
        b = DenseVecMatrix(rng.standard_normal((8, 8)))
        out = a.multiply(b, parallelism=999)  # clamps, full mesh
        np.testing.assert_allclose(
            out.to_numpy(), a.to_numpy() @ b.to_numpy(), rtol=1e-10
        )


class TestEngineCacheKeys:
    def test_axis_name_override_rebuilds_engines(self):
        # VERDICT r04 weak #6: the engine builders are cached on
        # (mesh, precision) but also read cfg.mesh_axis_rows/cols — a
        # config_override swapping the axis names on the SAME Mesh object
        # must rebuild, not serve the stale kernel. Shapes are chosen so the
        # stale kernel's shard specs don't divide: rows=6 splits over the
        # size-2 axis but NOT over the size-4 axis, so a stale spec either
        # crashes or silently mis-shards.
        import jax
        import jax.numpy as jnp

        from marlin_tpu.config import config_override

        mesh = mt.create_mesh((4, 2), axis_names=("x", "y"),
                              devices=jax.devices()[:8])
        rng = np.random.default_rng(7)
        a = rng.standard_normal((8, 12))
        b = rng.standard_normal((12, 10))
        for engine in ("summa", "gspmd", "cannon"):
            # Prime the cache with rows over the size-4 "x" axis. (cannon
            # falls back to summa on the non-square mesh — still exercises
            # the dispatch under both namings.)
            with config_override(mesh_axis_rows="x", mesh_axis_cols="y"):
                out = summa.matmul(jnp.asarray(a), jnp.asarray(b),
                                   mesh=mesh, engine=engine)
                np.testing.assert_allclose(np.asarray(out), a @ b,
                                           rtol=1e-10)
            # Same mesh, swapped naming: rows now over the size-2 "y" axis.
            with config_override(mesh_axis_rows="y", mesh_axis_cols="x"):
                a2 = rng.standard_normal((6, 12))  # 6 % 4 != 0: stale spec
                out = summa.matmul(jnp.asarray(a2), jnp.asarray(b),
                                   mesh=mesh, engine=engine)
                np.testing.assert_allclose(np.asarray(out), a2 @ b,
                                           rtol=1e-10)


class TestEngineAccumulators:
    def test_bf16_cannon_and_3d_accumulate_f32(self, rng):
        # Ones matrices: the exact product is k (= 1024), representable in
        # f32 but NOT in bf16 increments past 256 — a bf16 cross-step carry
        # would stall below the true value.
        import jax.numpy as jnp

        import jax

        import marlin_tpu as mt

        n = 1024
        a = jnp.ones((n, n), jnp.bfloat16)
        b = jnp.ones((n, n), jnp.bfloat16)
        square = mt.create_mesh(shape=(2, 2), devices=jax.devices()[:4])
        for engine in ("cannon", "summa"):
            out = summa.matmul(a, b, mesh=square, engine=engine)
            assert float(jnp.max(out.astype(jnp.float32))) == n, engine
        out3 = summa.matmul_3d(a, b, (2, 2, 2))
        assert float(jnp.max(out3.astype(jnp.float32))) == n
