"""Mesh-runtime tests (mesh.py): grid factorization, mesh construction,
and the layout shardings that define the distributed types."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu import mesh as mmesh


class TestSquarestGrid:
    @pytest.mark.parametrize(
        "n,expect",
        [(1, (1, 1)), (2, (2, 1)), (4, (2, 2)), (6, (3, 2)), (8, (4, 2)),
         (7, (7, 1)), (12, (4, 3)), (16, (4, 4)), (64, (8, 8))],
    )
    def test_factorization(self, n, expect):
        assert mmesh.squarest_grid(n) == expect


class TestCreateMesh:
    def test_default_uses_all_devices_squarest(self):
        m = mmesh.create_mesh()
        assert dict(m.shape) == {"mr": 4, "mc": 2}

    def test_explicit_shape(self):
        m = mmesh.create_mesh((2, 4))
        assert mmesh.axis_sizes(m) == (2, 4)

    def test_submesh(self):
        m = mmesh.create_mesh((2, 2), devices=jax.devices()[:4])
        assert len(list(m.devices.flat)) == 4

    def test_shape_device_mismatch_raises(self):
        with pytest.raises(ValueError):
            mmesh.create_mesh((3, 2), devices=jax.devices()[:4])

    def test_custom_axis_names(self):
        m = mmesh.create_mesh((2, 2), axis_names=("a", "b"),
                              devices=jax.devices()[:4])
        assert m.axis_names == ("a", "b")

    def test_default_mesh_is_cached(self):
        assert mmesh.default_mesh() is mmesh.default_mesh()


class TestShardings:
    """Each layout must place the shards its distributed type promises."""

    def _shard_shapes(self, arr, sharding):
        placed = jax.device_put(arr, sharding)
        return {s.data.shape for s in placed.addressable_shards}

    def test_row_sharding_stripes_rows(self):
        m = mmesh.default_mesh()
        shapes = self._shard_shapes(jnp.zeros((16, 6)), mmesh.row_sharding(m))
        assert shapes == {(2, 6)}  # 16 rows / 8 devices, cols whole

    def test_block_sharding_grid(self):
        m = mmesh.default_mesh()
        shapes = self._shard_shapes(jnp.zeros((16, 6)), mmesh.block_sharding(m))
        assert shapes == {(4, 3)}  # (16/4, 6/2)

    def test_col_sharding_stripes_cols(self):
        m = mmesh.default_mesh()
        shapes = self._shard_shapes(jnp.zeros((6, 16)), mmesh.col_sharding(m))
        assert shapes == {(6, 2)}

    def test_replicated_every_device_has_all(self):
        m = mmesh.default_mesh()
        shapes = self._shard_shapes(jnp.zeros((5, 7)), mmesh.replicated_sharding(m))
        assert shapes == {(5, 7)}

    def test_vector_sharding_chunks(self):
        m = mmesh.default_mesh()
        shapes = self._shard_shapes(jnp.zeros((24,)), mmesh.vector_sharding(m))
        assert shapes == {(3,)}

    def test_round_trip_preserves_values(self):
        m = mmesh.default_mesh()
        arr = np.arange(48.0).reshape(8, 6)
        for sh in (mmesh.row_sharding(m), mmesh.block_sharding(m),
                   mmesh.replicated_sharding(m)):
            placed = jax.device_put(jnp.asarray(arr), sh)
            np.testing.assert_array_equal(np.asarray(placed), arr)
