"""Native C++ layer tests: textio codec (ctypes) and the generate_matrix tool."""

import os
import subprocess

import numpy as np
import pytest

from marlin_tpu import native
from marlin_tpu.matrix.dense import DenseVecMatrix
from marlin_tpu.utils.io import load_dense_matrix

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")


@pytest.fixture(scope="module")
def native_ok():
    if not native.available():
        pytest.skip("native toolchain unavailable")
    return True


class TestTextIOCodec:
    def test_roundtrip(self, native_ok, rng):
        arr = rng.standard_normal((17, 9))
        text = native.format_dense_text(arr)
        back = native.parse_dense_text(text)
        np.testing.assert_allclose(back, arr)  # %.17g is exact for float64

    def test_parse_variants(self, native_ok):
        back = native.parse_dense_text(b"0:1.0,2.0\n2:5.0 6.0\n1:3.0, 4.0\n")
        np.testing.assert_allclose(back, [[1, 2], [3, 4], [5, 6]])

    def test_malformed_raises(self, native_ok):
        with pytest.raises(ValueError, match="line 2"):
            native.parse_dense_text(b"0:1.0,2.0\nnot-a-row\n")

    def test_matches_python_path(self, native_ok, rng, tmp_path):
        arr = rng.standard_normal((11, 6))
        p_native = str(tmp_path / "n")
        p_python = str(tmp_path / "p")
        m = DenseVecMatrix(arr)
        m.save_to_file_system(p_native)
        from marlin_tpu.utils.io import save_dense_matrix

        save_dense_matrix(m, p_python, use_native=False)
        a = load_dense_matrix(p_native, use_native=True).to_numpy()
        b = load_dense_matrix(p_python, use_native=False).to_numpy()
        np.testing.assert_allclose(a, arr)
        np.testing.assert_allclose(b, arr)


class TestGenerateMatrixTool:
    @pytest.fixture(scope="class")
    def binary(self, tmp_path_factory):
        build = tmp_path_factory.mktemp("tools")
        out = str(build / "generate_matrix")
        try:
            subprocess.run(
                ["g++", "-O2", "-std=c++17", "-o", out,
                 os.path.join(TOOLS, "generate_matrix.cpp")],
                check=True, capture_output=True, timeout=120,
            )
        except (OSError, subprocess.SubprocessError):
            pytest.skip("g++ unavailable")
        return out

    def test_output_loads_as_matrix(self, binary, tmp_path):
        out = subprocess.run(
            [binary, "8", "5", "7"], check=True, capture_output=True, timeout=60
        ).stdout
        f = tmp_path / "gen.txt"
        f.write_bytes(out)
        m = load_dense_matrix(str(f))
        assert m.shape == (8, 5)
        vals = m.to_numpy()
        assert (-1 <= vals).all() and (vals < 1).all()

    def test_deterministic_by_seed(self, binary):
        a = subprocess.run([binary, "4", "4", "9"], capture_output=True, timeout=60).stdout
        b = subprocess.run([binary, "4", "4", "9"], capture_output=True, timeout=60).stdout
        c = subprocess.run([binary, "4", "4", "10"], capture_output=True, timeout=60).stdout
        assert a == b and a != c

    def test_usage_error(self, binary):
        r = subprocess.run([binary], capture_output=True, timeout=60)
        assert r.returncode == 1 and b"usage" in r.stderr


class TestChunkParse:
    def test_parse_chunk_golden(self):
        if not native.available():
            pytest.skip("no toolchain")
        data = b"3:1.5,2.5\n0:7.0\n"
        idx, vals = native.parse_dense_chunk(data, 2)
        np.testing.assert_array_equal(idx, [3, 0])
        np.testing.assert_allclose(vals, [[1.5, 2.5], [7.0, 0.0]])

    def test_parse_chunk_malformed_raises(self):
        if not native.available():
            pytest.skip("no toolchain")
        with pytest.raises(ValueError):
            native.parse_dense_chunk(b"nonsense line\n", 2)

    def test_probe_matches_python(self):
        if not native.available():
            pytest.skip("no toolchain")
        data = b"0:1,2,3\n5:4\n"
        n_lines, max_idx, width = native.probe_dense_text(data)
        assert (n_lines, max_idx, width) == (2, 5, 3)
