"""Distributed tracing (docs/observability.md §10): X-Trace-Context
propagation, coherent fleet-wide retention, body-wins request-id
correlation, the trace stitcher, and the fleet-path overhead pin.

Layers:

* UNIT — obs/distributed.py: header mint/parse round-trip, tolerant
  parsing (malformed → standalone behavior), deterministic ids.
* PROPERTY — two REAL in-process HTTP replicas behind a simulated
  front door: for every sampled/unsampled/tail-kept interleaving, a
  kept request's trace is complete (root + children, zero dangling
  parents) on exactly the replica that served it, a dropped request's
  trace is absent entirely, and responses are identical (up to the
  measured ``timing`` block) with tracing on vs off.
* STITCH — tools/trace_stitch.py against the committed fixture
  (tests/data/fleet_trace/: real per-process exports from a traced
  2-replica fleet run): merges clean, ``--check`` passes in tier-1,
  and tampered artifacts fail the check.
* FLEET — a REAL traced fleet (subprocess replicas): exports stitch
  into one Perfetto-loadable timeline, the deadline-expired request is
  tail-kept at a 1/64 head rate, the flight recorder answers on the
  front door, and X-Request-Id precedence is body-wins in the replica
  runlog. Plus the 5%-overhead pin extended to the fleet path.
"""

import glob
import importlib.util
import http.client
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from marlin_tpu.models import TransformerConfig, init_params
from marlin_tpu.obs import distributed as dtrace
from marlin_tpu.obs.runlog import RunLog
from marlin_tpu.obs.trace import Tracer
from marlin_tpu.serving.server import serve

HOST = "127.0.0.1"
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURE = os.path.join(_REPO, "tests", "data", "fleet_trace")
_STITCH_CLI = os.path.join(_REPO, "tools", "trace_stitch.py")

# The per-request span names the retention verdict governs (the
# serving.http root and everything opened inside it on the handler
# thread). Engine-thread spans (serving.round and its children, incl.
# serving.admit) are round-timeline roots sampled by the replica's own
# rate and legitimately survive a dropped request.
_REQUEST_SPANS = ("serving.http", "serving.submit", "http.respond")


@pytest.fixture(scope="module")
def ts():
    spec = importlib.util.spec_from_file_location(
        "trace_stitch", _STITCH_CLI)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["trace_stitch"] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def model():
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=2,
                            n_layers=1, d_ff=128, max_len=64,
                            dtype="float32")
    return init_params(cfg, seed=0), cfg


def _post(port, body, headers=None, timeout=60.0):
    conn = http.client.HTTPConnection(HOST, port, timeout=timeout)
    try:
        conn.request("POST", "/v1/generate", json.dumps(body).encode(),
                     headers or {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read(), dict(resp.getheaders())
    finally:
        conn.close()


def _get(port, path, timeout=30.0):
    conn = http.client.HTTPConnection(HOST, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


# -- unit: the header -------------------------------------------------


class TestTraceContext:
    def test_mint_parse_round_trip(self):
        ctx = dtrace.mint(42, True)
        assert ctx.trace_id == dtrace.trace_id_for(42)
        assert ctx.span_id == dtrace.span_id_for(ctx.trace_id,
                                                 "fleet.request")
        hdr = ctx.to_header()
        assert hdr == f"00-{ctx.trace_id}-{ctx.span_id}-01"
        back = dtrace.parse(hdr)
        assert back == ctx

    def test_sampled_flag_round_trips_both_ways(self):
        assert dtrace.mint(7, False).to_header().endswith("-00")
        assert dtrace.mint(7, True).to_header().endswith("-01")
        assert dtrace.parse(dtrace.mint(7, False).to_header()) \
            .sampled is False

    def test_deterministic_ids(self):
        # No entropy enters the serving path: the same request id
        # always derives the same trace — a replayed/restarted request
        # re-attaches to its original timeline by construction.
        assert dtrace.mint(9, True) == dtrace.mint(9, True)
        assert dtrace.trace_id_for(9) != dtrace.trace_id_for(10)

    @pytest.mark.parametrize("bad", [
        None, "", "garbage",
        "01-" + "a" * 32 + "-" + "b" * 16 + "-01",  # unknown version
        "00-" + "a" * 31 + "-" + "b" * 16 + "-01",  # short trace id
        "00-" + "0" * 32 + "-" + "b" * 16 + "-01",  # all-zero trace id
        "00-" + "a" * 32 + "-" + "b" * 16,          # missing flags
    ])
    def test_malformed_headers_parse_none(self, bad):
        assert dtrace.parse(bad) is None


# -- property: coherent retention across two replicas ------------------


def _strip_timing(raw: bytes) -> dict:
    obj = json.loads(raw)
    obj.pop("timing", None)
    return obj


class TestRetentionCoherence:
    def _run_arm(self, model, traced: bool, pattern, prompts):
        """Serve ``pattern`` = [(sampled, tail), ...] across two REAL
        in-process HTTP replicas. The front door is simulated: an
        explicit body request_id (the router contract) plus a minted
        X-Trace-Context carrying the head verdict. ``tail`` rides a
        microscopic queue deadline — the request deterministically
        expires before admission (504, status != done), the engine's
        tail-retention trigger. Returns (responses, tracers)."""
        params, cfg = model
        servers, tracers = [], []
        for _ in range(2):
            tr = Tracer(enabled=traced, exemplar_k=4, flight_k=4)
            servers.append(serve(
                params, cfg, port=0, batch=2, round_steps=2,
                max_pending=16, seed=0, tracer=tr,
                runlog=RunLog()).start_background())
            tracers.append(tr)
        out = []
        try:
            for i, (sampled, tail) in enumerate(pattern):
                rid = 1000 + i
                body = {"prompt": prompts[i], "steps": 3,
                        "request_id": rid}
                if tail:
                    body["deadline_s"] = 1e-6
                headers = {"Content-Type": "application/json"}
                if traced:
                    headers[dtrace.TRACE_HEADER] = \
                        dtrace.mint(rid, sampled).to_header()
                st, data, hdrs = _post(servers[i % 2].port, body,
                                       headers)
                assert st == (504 if tail else 200), (st, data)
                # Byte-transparency on the wire: tracing adds no
                # response headers.
                assert dtrace.TRACE_HEADER not in hdrs
                out.append((st, data))
        finally:
            for s in servers:
                s.close_now()
        return out, tracers

    def test_all_interleavings_coherent_and_byte_identical(self, model):
        # Every (sampled, tail) combination, spread across both
        # replicas in both orders — 8 requests cover the 4 combos twice
        # with replica assignment flipped.
        pattern = [(s, t) for s in (True, False) for t in (True, False)]
        pattern = pattern + pattern[::-1]
        rng = np.random.default_rng(11)
        prompts = [rng.integers(1, 60, 5).tolist()
                   for _ in range(len(pattern))]
        on, tracers = self._run_arm(model, True, pattern, prompts)
        off, _ = self._run_arm(model, False, pattern, prompts)
        # Identical outputs, tracing on vs off: same status codes and
        # same bodies up to the measured timing block (tokens, ids,
        # status — the deterministic payload — byte-for-byte).
        assert [st for st, _ in on] == [st for st, _ in off]
        for (_, a), (_, b) in zip(on, off):
            assert _strip_timing(a) == _strip_timing(b)
        for i, (sampled, tail) in enumerate(pattern):
            rid = 1000 + i
            events = tracers[i % 2].events()
            other = tracers[(i + 1) % 2].events()
            req = [e for e in events
                   if e["name"] in _REQUEST_SPANS
                   and e.get("args", {}).get("request_id") == rid]
            # The OTHER replica never saw this request.
            assert not [e for e in other
                        if e.get("args", {}).get("request_id") == rid]
            if sampled or tail:
                # Kept: the remote-parent root is present, carries the
                # minted trace id, and every parent link resolves
                # within the export (no dangling parents).
                roots = [e for e in req if e["name"] == "serving.http"]
                assert len(roots) == 1, (rid, req)
                assert roots[0]["args"]["trace_id"] == \
                    dtrace.trace_id_for(rid)
                assert roots[0]["args"]["remote_parent"] == \
                    dtrace.span_id_for(dtrace.trace_id_for(rid),
                                       "fleet.request")
                names = {e["name"] for e in events}
                for e in req:
                    parent = e.get("args", {}).get("parent")
                    assert parent is None or parent in names, e
            else:
                # Dropped: the request's trace is absent ENTIRELY.
                assert req == [], (rid, req)

    def test_tail_promotion_never_duplicates_head_kept(self, model):
        # A request that is BOTH head-sampled and tail-kept (sampled
        # deadline miss) appears exactly once per span name.
        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, 60, 5).tolist()]
        _, tracers = self._run_arm(model, True, [(True, True)], prompts)
        req = [e for e in tracers[0].events()
               if e.get("args", {}).get("request_id") == 1000]
        names = [e["name"] for e in req]
        assert len(names) == len(set(names)), names


# -- body-wins X-Request-Id precedence (PR 17 convention) -------------


class TestBodyWinsCorrelation:
    def test_header_rides_as_correlation_only(self, model):
        params, cfg = model
        runlog = RunLog()
        srv = serve(params, cfg, port=0, batch=2, round_steps=2,
                    max_pending=8, seed=0,
                    tracer=Tracer(enabled=True, exemplar_k=2,
                                  flight_k=2),
                    runlog=runlog).start_background()
        try:
            ctx = dtrace.mint(7007, True)
            st, data, hdrs = _post(
                srv.port,
                {"prompt": [1, 2, 3], "steps": 2, "request_id": 7007},
                {"Content-Type": "application/json",
                 "X-Request-Id": "corr-abc",
                 dtrace.TRACE_HEADER: ctx.to_header()})
            assert st == 200
            obj = json.loads(data)
            # Engine identity is the BODY's router-assigned id; the
            # caller's header comes back verbatim as correlation.
            assert obj["request_id"] == 7007
            assert hdrs["X-Engine-Request-Id"] == "7007"
            assert hdrs["X-Request-Id"] == "corr-abc"
        finally:
            srv.close_now()
        # The runlog joins all three identities on the engine key.
        (ev,) = runlog.events("trace_ctx")
        assert ev["request_id"] == 7007
        assert ev["http_id"] == "corr-abc"
        assert ev["trace_id"] == ctx.trace_id
        assert ev["sampled"] is True
        # The engine's own timeline is keyed on the body id — the
        # header id never becomes a runlog key.
        assert any(e["request_id"] == 7007
                   for e in runlog.events("submit"))
        assert not any(e.get("request_id") == "corr-abc"
                       for e in runlog.events())

    def test_correlation_without_trace_context(self, model):
        # Pre-fleet callers: X-Request-Id alone still correlates.
        params, cfg = model
        runlog = RunLog()
        srv = serve(params, cfg, port=0, batch=2, round_steps=2,
                    max_pending=8, seed=0,
                    runlog=runlog).start_background()
        try:
            st, data, _ = _post(
                srv.port, {"prompt": [1, 2, 3], "steps": 2},
                {"Content-Type": "application/json",
                 "X-Request-Id": "solo-1"})
            assert st == 200
            rid = json.loads(data)["request_id"]
        finally:
            srv.close_now()
        (ev,) = runlog.events("trace_ctx")
        assert ev["request_id"] == rid and ev["http_id"] == "solo-1"
        assert "trace_id" not in ev


# -- the stitcher against the committed fixture ------------------------


def _fixture_paths():
    return [os.path.join(_FIXTURE, n) for n in
            ("frontdoor.trace.json", "replica0.trace.json",
             "replica1.trace.json")]


class TestStitchFixture:
    def test_fixture_stitches_clean(self, ts):
        paths = _fixture_paths()
        doc = ts.stitch([(p, ts.load_trace(p)) for p in paths])
        assert ts.check(doc) == []
        evs = doc["traceEvents"]
        assert doc["metadata"]["n_processes"] == 3
        # One flow arrow per fleet hop: every head-kept request links
        # its fleet.request span to the replica's serving.http root.
        starts = [e for e in evs if e.get("ph") == "s"]
        finishes = [e for e in evs if e.get("ph") == "f"]
        assert len(starts) == len(finishes) == 3
        assert {e["id"] for e in starts} == {e["id"] for e in finishes}
        # Clock alignment: every arrow points forward in stitched time.
        fin_ts = {e["id"]: e["ts"] for e in finishes}
        for s in starts:
            assert fin_ts[s["id"]] >= s["ts"]
        # Distinct pids per process, metadata names them for Perfetto.
        assert {e["pid"] for e in evs} == {0, 1, 2}
        meta = {e["pid"]: e["args"]["name"] for e in evs
                if e.get("ph") == "M" and e["name"] == "process_name"}
        assert meta[0] == "fleet.frontdoor"

    def test_cli_stitch_and_check_exit_zero(self, ts, tmp_path):
        out = str(tmp_path / "stitched.json")
        r = subprocess.run(
            [sys.executable, _STITCH_CLI, *_fixture_paths(), "-o", out],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        r = subprocess.run([sys.executable, _STITCH_CLI, "--check", out],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_check_rejects_tampering(self, ts, tmp_path):
        paths = _fixture_paths()
        clean = ts.stitch([(p, ts.load_trace(p)) for p in paths])

        def tampered(mutate):
            doc = json.loads(json.dumps(clean))
            mutate(doc)
            return ts.check(doc)

        def drop_flow_finish(doc):
            evs = doc["traceEvents"]
            evs.remove(next(e for e in evs if e.get("ph") == "f"))

        def dangle_parent(doc):
            span = next(e for e in doc["traceEvents"]
                        if e.get("ph") == "X")
            span.setdefault("args", {})["parent"] = "no.such.span"

        def scramble_clock(doc):
            spans = [e for e in doc["traceEvents"]
                     if e.get("ph") == "X"]
            spans[-1]["ts"] = spans[0]["ts"] - 1e9

        def break_schema(doc):
            doc["traceEvents"] = "nope"

        for mutate in (drop_flow_finish, dangle_parent,
                       scramble_clock, break_schema):
            assert tampered(mutate), mutate.__name__
        # And the CLI exit code carries the verdict.
        bad = json.loads(json.dumps(clean))
        drop_flow_finish(bad)
        path = str(tmp_path / "tampered.json")
        with open(path, "w") as f:
            json.dump(bad, f)
        r = subprocess.run([sys.executable, _STITCH_CLI, "--check",
                            path], capture_output=True, text=True)
        assert r.returncode == 1


# -- the real fleet: propagation, tail retention, flight recorder ------


class TestFleetTracing:
    def test_traced_fleet_stitches_and_tail_keeps(self, fleet_factory,
                                                  tmp_path, ts):
        trace_dir = str(tmp_path / "traces")
        server = fleet_factory(n_replicas=2, trace=True,
                               trace_sample=1.0 / 64,
                               trace_export_dir=trace_dir)
        port = server.port
        rids = []
        for i in range(4):
            st, data, hdrs = _post(port, {"prompt": [1 + i, 2, 3],
                                          "steps": 3})
            assert st == 200, (st, data)
            rids.append(json.loads(data)["request_id"])
        # A deadline-expired request: 504, status != done — must be
        # tail-kept in FULL despite the 1/64 head rate.
        st, data, _ = _post(port, {"prompt": [9, 9, 9], "steps": 3,
                                   "deadline_s": 1e-6})
        assert st == 504, (st, data)
        expired_rid = json.loads(data)["request_id"]
        # Flight recorder answers on the FRONT DOOR (and replicas).
        st, body = _get(port, "/debug/trace?flight=1")
        assert st == 200
        flight = json.loads(body)["traceEvents"]
        assert any(e.get("args", {}).get("request_id") is not None
                   for e in flight)
        assert server.begin_drain(120.0)
        paths = sorted(glob.glob(os.path.join(trace_dir,
                                              "*.trace.json")))
        assert len(paths) == 3  # frontdoor + 2 replica incarnations
        doc = ts.stitch([(p, ts.load_trace(p)) for p in paths])
        assert ts.check(doc) == []
        stitched_rids = {e["args"]["request_id"]
                         for e in doc["traceEvents"]
                         if e.get("args", {}).get("request_id")
                         is not None}
        # The expired request's trace survived tail retention; its
        # serving.http root is present on whichever replica served it.
        assert expired_rid in stitched_rids
        assert any(e["name"] == "serving.http"
                   and e["args"].get("request_id") == expired_rid
                   for e in doc["traceEvents"])

    def test_body_wins_through_the_front_door(self, fleet_factory,
                                              tmp_path):
        runlog_dir = str(tmp_path / "runlogs")
        server = fleet_factory(n_replicas=2, runlog_dir=runlog_dir,
                               trace=True, trace_sample=1.0)
        st, data, hdrs = _post(server.port,
                               {"prompt": [1, 2, 3], "steps": 2},
                               {"Content-Type": "application/json",
                                "X-Request-Id": "caller-77"})
        assert st == 200
        rid = json.loads(data)["request_id"]
        assert hdrs["X-Request-Id"] == "caller-77"
        assert hdrs["X-Engine-Request-Id"] == str(rid)
        assert server.begin_drain(120.0)
        ctx_events = []
        for path in glob.glob(os.path.join(runlog_dir,
                                           "replica*.jsonl")):
            with open(path) as f:
                for line in f:
                    ev = json.loads(line)
                    if ev.get("kind") == "trace_ctx":
                        ctx_events.append(ev)
        (ev,) = [e for e in ctx_events if e.get("http_id")]
        assert ev["request_id"] == rid  # body id is the runlog key
        assert ev["http_id"] == "caller-77"  # header = correlation
        assert ev["trace_id"] == dtrace.trace_id_for(rid)


# -- the 5% pin, fleet path -------------------------------------------


class TestFleetOverhead:
    def test_traced_fleet_within_5pct_of_untraced(self, fleet_factory,
                                                  tmp_path):
        # The PR-3/PR-4 instrumentation pin extended to the fleet path:
        # front door + 2 replicas with tracing enabled (1/64 head
        # sampling + tail retention + flight rings) vs the same fleet
        # untraced, identical workloads. Same measurement discipline as
        # tests/test_obs.py: arms INTERLEAVE so machine drift hits
        # both, and min-of-trials OR median-of-trials within 1.05x
        # passes (a real overhead fails both estimators, a scheduler
        # hiccup cannot). Requests decode 40 steps so the trial window
        # is decode-dominated — per-request fixed costs (HTTP framing,
        # port-to-port variance between two distinct fleets) would
        # otherwise swamp a 5% pin on a ~25 ms window.
        arms = {
            "off": fleet_factory(
                n_replicas=2,
                runlog_dir=str(tmp_path / "rl_off")),
            "on": fleet_factory(
                n_replicas=2, trace=True, trace_sample=1.0 / 64,
                runlog_dir=str(tmp_path / "rl_on"),
                trace_export_dir=str(tmp_path / "tr_on")),
        }
        rng = np.random.default_rng(5)
        prompts = [rng.integers(1, 60, 6).tolist() for _ in range(4)]

        def trial(server):
            t0 = time.perf_counter()
            for p in prompts:
                st, data, _ = _post(server.port,
                                    {"prompt": p, "steps": 40})
                assert st == 200, (st, data)
            return time.perf_counter() - t0

        for server in arms.values():  # warmup: compiles out of band
            trial(server)
        times = {name: [] for name in arms}
        for _ in range(8):
            for name, server in arms.items():
                times[name].append(trial(server))
        med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
        # Trimmed mean (drop the 2 slowest trials) is the most stable
        # of the three against one-off scheduler spikes; min and median
        # each key off a single order statistic of 8 samples and swing
        # several percent between two OS-distinct fleet instances even
        # at zero true overhead.
        tmean = lambda xs: sum(sorted(xs)[:-2])  # noqa: E731
        ok_min = min(times["on"]) <= min(times["off"]) * 1.05
        ok_med = med(times["on"]) <= med(times["off"]) * 1.05
        ok_tmean = tmean(times["on"]) <= tmean(times["off"]) * 1.05
        assert ok_min or ok_med or ok_tmean, times
