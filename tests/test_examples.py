"""Smoke tests for every example CLI — the layer the reference only ran via
spark-submit (SURVEY.md §2.6), exercised here in-process on the CPU mesh."""

import json
import os

import numpy as np
import pytest

from marlin_tpu.examples import (
    als as als_ex,
    blas1,
    blas3,
    logistic_regression,
    matrix_lu_decompose,
    matrix_multiply,
    neural_network,
    page_rank,
    rmm_compare,
    sparse_multiply,
)


def test_matrix_multiply_random(capsys):
    matrix_multiply.main(["64", "48", "32", "--check", "--iters", "1"])
    out = json.loads(capsys.readouterr().out)
    assert out["matches_oracle"] is True


def test_matrix_multiply_files(tmp_path, rng, capsys):
    # BASELINE config #1 shape: file-loaded A x B.
    from marlin_tpu.matrix.dense import DenseVecMatrix

    a = rng.standard_normal((20, 20))
    b = rng.standard_normal((20, 20))
    pa, pb = str(tmp_path / "a"), str(tmp_path / "b")
    DenseVecMatrix(a).save_to_file_system(pa)
    DenseVecMatrix(b).save_to_file_system(pb)
    matrix_multiply.main(
        ["--file-a", pa, "--file-b", pb, "--check", "--iters", "1",
         "--output", str(tmp_path / "c")]
    )
    out = json.loads(capsys.readouterr().out)
    assert out["matches_oracle"] is True
    from marlin_tpu.utils.io import load_dense_matrix

    np.testing.assert_allclose(
        load_dense_matrix(str(tmp_path / "c")).to_numpy(), a @ b, rtol=1e-8
    )


@pytest.mark.parametrize("mode", ["dist", "local"])
def test_blas1(mode, capsys):
    blas1.main(["1000", "--mode", mode])
    out = json.loads(capsys.readouterr().out)
    assert abs(out["dot"] - 250.0) < 25  # E[dot] = n/4 for U(0,1)


def test_blas3(capsys):
    blas3.main(["32", "24", "16", "--grid", "2", "2", "2"])
    out = json.loads(capsys.readouterr().out)
    assert set(out["seconds"]) == {"local", "broadcast", "split"}


def test_rmm_compare(capsys):
    rmm_compare.main(["32", "32", "32"])
    out = json.loads(capsys.readouterr().out)
    assert "rmm_3d_grid" in out["seconds"] and "summa_allgather" in out["seconds"]


def test_sparse_multiply(capsys):
    sparse_multiply.main(["40", "40", "40", "--sparsity", "0.1"])
    out = json.loads(capsys.readouterr().out)
    assert len(out["seconds"]) == 6


@pytest.mark.slow
def test_sparse_multiply_ell_regime(capsys):
    # Low enough density that mode 1's auto dispatch takes the ELL
    # row-gather arm (and the lazy result's .values path in the CLI fence).
    sparse_multiply.main(["256", "256", "256", "--sparsity", "0.003",
                          "--modes", "1", "3"])
    out = json.loads(capsys.readouterr().out)
    assert "1_sparse_x_sparse" in out["seconds"]
    assert "3_sparse_x_dense" in out["seconds"]


def test_lu_example(tmp_path, rng, capsys):
    from marlin_tpu.matrix.dense import DenseVecMatrix
    from marlin_tpu.linalg import unpack_lu
    from marlin_tpu.utils.io import load_block_matrix

    a = rng.standard_normal((12, 12))
    src = str(tmp_path / "in")
    DenseVecMatrix(a).save_to_file_system(src)
    dst = str(tmp_path / "out")
    matrix_lu_decompose.main([src, dst, "--mode", "breeze"])
    packed = load_block_matrix(dst).to_numpy()
    perm = np.loadtxt(os.path.join(dst, "_pivots"), dtype=int)
    l, u = unpack_lu(packed)
    np.testing.assert_allclose(l @ u, a[perm], rtol=1e-8, atol=1e-8)


def test_als_example(tmp_path, rng, capsys):
    lines = []
    for u in range(8):
        for p in range(6):
            if rng.random() < 0.6:
                lines.append(f"{u},{p},{rng.integers(1, 6)}")
    src = tmp_path / "ratings.txt"
    src.write_text("\n".join(lines))
    als_ex.main([str(src), str(tmp_path / "factors"), "--rank", "2",
                 "--iterations", "3", "--seed", "1"])
    out = json.loads(capsys.readouterr().out)
    assert out["nnz"] == len(lines)
    assert (tmp_path / "factors" / "userFeatures" / "_SUCCESS").exists()
    assert (tmp_path / "factors" / "productFeatures" / "_SUCCESS").exists()


def test_logistic_regression_synthetic(capsys):
    logistic_regression.main(["--synthetic", "300", "5", "--iters", "200",
                              "--step-size", "5.0"])
    out = json.loads(capsys.readouterr().out)
    assert out["train_accuracy"] > 0.9


def test_page_rank(capsys, tmp_path):
    # Star graph: everyone links to node 0 -> node 0 must rank first.
    lines = [f"{i} 0" for i in range(1, 6)] + ["0 1"]
    src = tmp_path / "links.txt"
    src.write_text("\n".join(f"{l} 1.0" for l in lines))
    page_rank.main([str(src), "--iterations", "30"])
    out = json.loads(capsys.readouterr().out)
    assert out["top5"][0][0] == 0
    assert abs(out["rank_sum"] - 1.0) < 0.2


def test_neural_network(tmp_path, capsys):
    neural_network.main(
        ["--synthetic", "256", "--d-in", "32", "--d-out", "4", "--hidden", "16",
         "--batch-size", "64", "--iterations", "30", "--output", str(tmp_path / "w")]
    )
    out = json.loads(capsys.readouterr().out)
    assert out["final_loss"] < 2.0
    assert (tmp_path / "w" / "hidden.csv").exists()


def test_neural_network_learns(rng):
    # Loss must actually decrease on a learnable mapping.
    from marlin_tpu.examples.neural_network import forward, init_params, train

    raw = rng.random((2048, 16))
    margin = np.abs(raw.sum(axis=1) - 8) > 0.8  # keep well-separated samples
    images = raw[margin][:512]
    classes = (images.sum(axis=1) > 8).astype(int)
    labels = np.eye(2)[classes]
    params, loss = train(images, labels, hidden=16, batch_size=128,
                         iterations=300, learning_rate=2.0, seed=0)
    import jax.numpy as jnp

    pred = np.asarray(forward(params, jnp.asarray(images, jnp.float32)))
    acc = (pred.argmax(1) == classes).mean()
    assert acc > 0.9, f"NN failed to learn, acc={acc}, loss={loss}"


# The two heaviest example CLIs (~12 s and ~9 s of compile) run under
# -m slow; the other seven examples keep the CLI contract in tier-1
# (ROADMAP 9 wall-clock budget).
@pytest.mark.slow
def test_transformer_lm(capsys):
    from marlin_tpu.examples import transformer_lm

    assert transformer_lm.main(["3", "2", "32", "32"]) == 0
    out = capsys.readouterr().out
    assert "TransformerLM" in out and "tok/s" in out


def test_long_context(capsys):
    from marlin_tpu.examples import long_context

    assert long_context.main(["256", "8", "16"]) == 0
    out = capsys.readouterr().out
    assert "engines agree" in out


def test_gcn_example(capsys):
    from marlin_tpu.examples import gcn

    assert gcn.main(["128", "40"]) == 0
    assert "test accuracy" in capsys.readouterr().out


def test_least_squares(capsys):
    import json

    from marlin_tpu.examples import least_squares

    assert least_squares.main(["2000", "12", "--mode", "tsqr"]) == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["example"] == "LeastSquares"
    assert line["coef_max_err"] < 0.05  # recovers the planted coefficients
    assert line["qr_orth_err"] < 1e-6
