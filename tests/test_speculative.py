"""Prompt-lookup speculative decoding (models/transformer.py
decode_chunk + generate_speculative).

THE oracle: speculation changes the schedule, never the distribution —
speculative greedy output must equal plain greedy ``generate`` EXACTLY,
token for token, on every config variant and prompt shape. decode_chunk
gets its own parity bar against sequential decode_step calls (same cache
evolution, same logits to float roundoff)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from marlin_tpu.models import transformer as tr
from marlin_tpu.models import (TransformerConfig, generate,
                               generate_speculative, init_kv_cache,
                               init_params, quantize_params_int8)


def _cfg(**kw):
    base = dict(vocab=48, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                max_len=96)
    base.update(kw)
    return TransformerConfig(**base)


class TestDecodeChunk:
    @pytest.mark.parametrize("kw", [
        {},
        {"rope": True, "n_kv_heads": 1},
        {"dtype": "bfloat16"},
        {"kv_quant": "int8"},
    ])
    def test_matches_sequential_decode_steps(self, kw):
        cfg = _cfg(**kw)
        p = init_params(cfg, seed=1)
        b, c, pos0 = 2, 4, 3
        toks = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab, (b, c)),
            jnp.int32)
        cache1 = init_kv_cache(cfg, b, dtype=jnp.dtype(cfg.dtype))
        cache2 = init_kv_cache(cfg, b, dtype=jnp.dtype(cfg.dtype))
        lc, cache1 = tr.decode_chunk(p, cache1, toks, pos0, cfg)
        seq = []
        for i in range(c):
            li, cache2 = tr.decode_step(p, cache2, toks[:, i], pos0 + i,
                                        cfg)
            seq.append(li)
        ls = jnp.stack(seq, axis=1)
        np.testing.assert_allclose(
            np.asarray(lc, np.float32), np.asarray(ls, np.float32),
            atol=5e-7 if cfg.dtype == "float32" else 5e-2, rtol=1e-5)
        # The caches agree too (chunk wrote the same slots).
        for l1, l2 in zip(cache1, cache2):
            for k in l1:
                np.testing.assert_allclose(
                    np.asarray(l1[k], np.float32),
                    np.asarray(l2[k], np.float32), atol=5e-7, rtol=1e-5)

    def test_rejects_ring_cache(self):
        cfg = _cfg(window=8)
        p = init_params(cfg, seed=0)
        cache = init_kv_cache(cfg, 1)
        with pytest.raises(NotImplementedError, match="ring"):
            tr.decode_chunk(p, cache, jnp.zeros((1, 3), jnp.int32), 0, cfg)


class TestSpeculativeGeneration:
    @pytest.mark.parametrize("kw", [
        {},
        {"rope": True, "n_kv_heads": 1},
        {"dtype": "bfloat16"},
    ])
    @pytest.mark.parametrize("kind", ["repetitive", "random"])
    def test_exactly_equals_plain_greedy(self, kw, kind):
        cfg = _cfg(**kw)
        p = init_params(cfg, seed=3)
        if kind == "repetitive":  # real acceptances: cyclic pattern
            pr = np.tile(np.array([5, 9, 17, 3]), 6)[:20]
        else:  # adversarial: ~zero acceptances, graceful degradation
            pr = np.random.default_rng(7).integers(0, cfg.vocab, 20)
        prompt = jnp.asarray(pr[None], jnp.int32)
        steps = 18
        base = np.asarray(generate(p, prompt, steps, cfg))
        spec = np.asarray(
            generate_speculative(p, prompt, steps, cfg, draft_len=6))
        if cfg.dtype == "bfloat16":
            # Untrained bf16 logits can near-tie; the chunked reduction
            # order may break a tie differently (docstring contract). A
            # flipped token derails the greedy continuation from there,
            # so compare the prefix up to the first divergence and bound
            # how early that may happen.
            agree = base[0] == spec[0]
            first_diff = int(np.argmin(agree)) if not agree.all() else steps
            assert first_diff >= steps // 2
        else:
            assert np.array_equal(base, spec)

    def test_full_int8_stack_composition(self):
        cfg = _cfg(kv_quant="int8", dtype="bfloat16")
        p = quantize_params_int8(init_params(cfg, seed=4))
        prompt = jnp.asarray(np.tile([7, 2, 31], 5)[None], jnp.int32)
        steps = 12
        base = generate(p, prompt, steps, cfg)
        spec = generate_speculative(p, prompt, steps, cfg, draft_len=5)
        assert np.array_equal(np.asarray(base), np.asarray(spec))

    def test_draft_len_sweep_all_exact(self):
        cfg = _cfg()
        p = init_params(cfg, seed=5)
        prompt = jnp.asarray(np.tile([1, 2, 3, 4, 5], 4)[None], jnp.int32)
        base = generate(p, prompt, 16, cfg)
        for dl in (2, 3, 8):
            spec = generate_speculative(p, prompt, 16, cfg, draft_len=dl)
            assert np.array_equal(np.asarray(base), np.asarray(spec)), dl

    def test_sampled_spec_kernel_preserves_distribution(self):
        # The distributional oracle for delta-draft speculative sampling,
        # on the PURE kernel (no model in the loop): over many keys, the
        # first emitted token's empirical distribution must equal the
        # target p exactly — accept-draft w.p. p(d) plus
        # resample-excluding-d contributes (1 - p(d)) * p(x)/(1 - p(d)).
        rng = np.random.default_rng(0)
        v, c = 7, 4
        logits = jnp.asarray(rng.standard_normal((c, v)), jnp.float32)
        lp = jax.nn.log_softmax(logits, axis=-1)
        drafts = jnp.asarray([2, 5, 2], jnp.int32)
        n = 60_000
        keys = jax.random.split(jax.random.PRNGKey(1), n)
        emit, m = jax.vmap(lambda k: tr._spec_emit(lp, drafts, k))(keys)
        emit = np.asarray(emit)
        m = np.asarray(m)
        p0 = np.asarray(jnp.exp(lp[0]))
        counts = np.bincount(emit[:, 0], minlength=v) / n
        np.testing.assert_allclose(counts, p0, atol=0.01)
        # Acceptance frequency of the first draft matches p0(d0).
        np.testing.assert_allclose((m >= 1).mean(), p0[2], atol=0.01)
        # Conditioned on the chain reaching position 1, its token is
        # p1-distributed.
        reached = m >= 1
        p1 = np.asarray(jnp.exp(lp[1]))
        c1 = np.bincount(emit[reached, 1], minlength=v) / reached.sum()
        np.testing.assert_allclose(c1, p1, atol=0.015)
        # A rejection at position 0 never re-emits the rejected draft.
        rej = m == 0
        assert not (emit[rej, 0] == 2).any()

    def test_sampled_spec_end_to_end(self):
        cfg = _cfg()
        p = init_params(cfg, seed=6)
        prompt = jnp.asarray(np.tile([3, 8, 1, 4], 5)[None], jnp.int32)
        out = generate_speculative(p, prompt, 16, cfg, draft_len=5,
                                   temperature=0.8, seed=11)
        assert out.shape == (1, 16)
        o = np.asarray(out)
        assert o.min() >= 0 and o.max() < cfg.vocab
        # Determinism under a fixed seed; a different seed moves it.
        out2 = generate_speculative(p, prompt, 16, cfg, draft_len=5,
                                    temperature=0.8, seed=11)
        assert np.array_equal(o, np.asarray(out2))
        out3 = generate_speculative(p, prompt, 16, cfg, draft_len=5,
                                    temperature=0.8, seed=12)
        assert not np.array_equal(o, np.asarray(out3))

    @pytest.mark.parametrize("kw", [
        {},
        {"rope": True, "n_kv_heads": 1},
        {"kv_quant": "int8"},
    ])
    def test_batched_matches_per_sequence_runs(self, kw):
        # Batched speculation: sequences desynchronize (per-seq positions
        # through decode_chunk) but each must produce EXACTLY what its own
        # B=1 run produces — and plain batch greedy agrees too. Mixed
        # prompts so acceptance rates genuinely differ across the batch;
        # rope and int8-cache variants exercise the per-sequence position
        # and scale-buffer write paths.
        cfg = _cfg(**kw)
        p = init_params(cfg, seed=9)
        prompts = np.stack([
            np.tile([5, 9, 17, 3], 5),          # repetitive: long accepts
            np.random.default_rng(3).integers(0, cfg.vocab, 20),  # random
            np.tile([1, 2], 10),                # short cycle
        ])
        batch = jnp.asarray(prompts, jnp.int32)
        steps = 14
        spec_b = np.asarray(
            generate_speculative(p, batch, steps, cfg, draft_len=5))
        base_b = np.asarray(generate(p, batch, steps, cfg))
        assert np.array_equal(spec_b, base_b)
        for i in range(3):
            solo = np.asarray(generate_speculative(
                p, batch[i:i + 1], steps, cfg, draft_len=5))
            assert np.array_equal(spec_b[i:i + 1], solo), i

    def test_skewed_batch_freezes_finished_sequences(self):
        # The skew fix (advisor r05 low #4): in a batch with deliberately
        # skewed completion — a repetitive prompt that accepts near-full
        # chunks next to a random prompt that accepts ~1 token per chunk —
        # finished sequences FREEZE: (a) outputs stay bit-identical to the
        # pre-fix oracle (plain batched greedy AND each sequence's own
        # B=1 run), and (b) the per-sequence verify-chunk counter stops at
        # each member's own finish, so the early finisher reports fewer
        # verify chunks than the slowest member (whose count == the loop's
        # iteration total).
        cfg = _cfg()
        p = init_params(cfg, seed=9)
        prompts = np.stack([
            np.tile([5, 9, 17, 3], 5),                            # fast
            np.random.default_rng(3).integers(0, cfg.vocab, 20),  # slow
            np.tile([1, 2], 10),                                  # middle
        ])
        batch = jnp.asarray(prompts, jnp.int32)
        steps = 14
        out, stats = generate_speculative(p, batch, steps, cfg,
                                          draft_len=5, return_stats=True)
        base = np.asarray(generate(p, batch, steps, cfg))
        assert np.array_equal(np.asarray(out), base)
        for i in range(3):
            solo = np.asarray(generate_speculative(
                p, batch[i:i + 1], steps, cfg, draft_len=5))
            assert np.array_equal(np.asarray(out)[i:i + 1], solo), i
        v = np.asarray(stats["verify_chunks"])
        iters = int(np.asarray(stats["iterations"]))
        assert v.max() == iters  # the slowest member was live throughout
        assert v.min() >= 1
        # The skew claim itself: the early finishers stopped verifying
        # well before the slowest member — without the freeze every
        # member's count would equal the iteration total.
        assert v[0] < v[1], (v, iters)
        assert v[2] < v[1], (v, iters)
        # A member alone finishes in the same number of verify chunks it
        # reports inside the skewed batch (per-row independence).
        for i in range(3):
            _, solo_stats = generate_speculative(
                p, batch[i:i + 1], steps, cfg, draft_len=5,
                return_stats=True)
            assert int(np.asarray(solo_stats["iterations"])) == v[i], i

    def test_guards(self):
        cfg = _cfg()
        p = init_params(cfg, seed=0)
        pr = jnp.zeros((1, 8), jnp.int32)
        with pytest.raises(NotImplementedError, match="dense cache"):
            generate_speculative(p, pr, 4, _cfg(window=8))
        with pytest.raises(ValueError, match="ngram"):
            generate_speculative(p, jnp.zeros((1, 1), jnp.int32), 4, cfg)
        with pytest.raises(ValueError, match="draft_len"):
            generate_speculative(p, pr, 4, cfg, draft_len=1)
        with pytest.raises(ValueError, match="max_len"):
            generate_speculative(p, pr, cfg.max_len, cfg)
        moe_cfg = _cfg(n_experts=2)
        moe_p = init_params(moe_cfg, seed=0)
        with pytest.raises(NotImplementedError, match="MoE"):
            generate_speculative(moe_p, pr, 4, moe_cfg)
        with pytest.raises(NotImplementedError, match="MoE"):
            tr.decode_chunk(moe_p, init_kv_cache(moe_cfg, 1),
                            jnp.zeros((1, 3), jnp.int32), 0, moe_cfg)
