"""End-to-end journey of a user migrating from the reference.

Mirrors the reference's documented workflow end to end on the shipped
sample data (README.md:21-27; data/a.100.100 x data/b.100.100 is the
BASELINE config #1 input): load text matrices, auto-dispatch multiply,
convert, decompose, save and reload — all through the public API only, the
way `examples/matrix_multiply.py` and `examples/matrix_lu_decompose.py`
drive it. A failure here means a migrating Marlin user hits a wall even if
every unit test passes.
"""

import numpy as np

import marlin_tpu as mt
from marlin_tpu.utils import io as mio


def test_reference_workflow_end_to_end(tmp_path):
    # Load the reference-format sample data (loadMatrixFile parity).
    a = mio.load_dense_matrix("data/a.100.100")
    b = mio.load_dense_matrix("data/b.100.100")
    assert a.shape == (100, 100) and b.shape == (100, 100)

    # Auto-dispatch multiply (MatrixMultiply.scala:46 call shape).
    c = a.multiply(b)
    ref = a.to_numpy().astype(np.float64) @ b.to_numpy().astype(np.float64)
    np.testing.assert_allclose(c.to_numpy(), ref, rtol=1e-4, atol=1e-4)

    # Block view + re-grid (toBlockMatrix parity), elementwise, reductions.
    cb = c.to_dense_vec_matrix() if hasattr(c, "to_dense_vec_matrix") else c
    s = cb.add(cb).sum()
    np.testing.assert_allclose(s, 2 * ref.sum(), rtol=1e-3)

    # LU on the product (MatrixLUDecompose.scala:40-49 journey).
    lu_mat, perm = cb.lu_decompose(mode="local")
    from marlin_tpu.linalg.lu import unpack_lu

    l, u = unpack_lu(lu_mat.to_numpy().astype(np.float64))
    np.testing.assert_allclose(
        l @ u, cb.to_numpy().astype(np.float64)[perm], rtol=1e-2, atol=1e-2)

    # Save in the reference text format, reload, compare (saveToFileSystem
    # -> loadMatrixFile round trip).
    out = str(tmp_path / "c_out")
    cb.save_to_file_system(out)
    back = mio.load_dense_matrix(out)
    np.testing.assert_allclose(back.to_numpy(), cb.to_numpy(), rtol=1e-5)
