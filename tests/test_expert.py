"""Expert-parallel (MoE top-1) routing vs a dense oracle on the 8-device mesh."""

import jax.numpy as jnp
import numpy as np
import pytest

from marlin_tpu.parallel.expert import expert_parallel_apply


def _linear_expert(w, x):
    return x @ w


def _oracle(ws, x, gates, cap_per_bucket, n_exp):
    """Dense reference: top-1 expert scaled by gate prob; per-(source shard,
    expert) buckets overflow to identity passthrough in local arrival order."""
    t, d = x.shape
    t_loc = t // n_exp
    probs = np.exp(gates - gates.max(1, keepdims=True))
    probs /= probs.sum(1, keepdims=True)
    expert = gates.argmax(1)
    out = x.copy()
    for shard in range(n_exp):
        counts = np.zeros(n_exp, int)
        for tk in range(shard * t_loc, (shard + 1) * t_loc):
            e = expert[tk]
            if counts[e] < cap_per_bucket:
                out[tk] = (x[tk] @ ws[e]) * probs[tk, e]
            counts[e] += 1
    return out


class TestExpertParallel:
    def test_matches_oracle_no_drops(self, rng, mesh):
        n_exp = len(mesh.devices.flat)
        t, d = n_exp * 8, 16
        ws = rng.standard_normal((n_exp, d, d)) * 0.3
        x = rng.standard_normal((t, d))
        gates = rng.standard_normal((t, n_exp))
        got = np.asarray(expert_parallel_apply(
            _linear_expert, jnp.asarray(ws), jnp.asarray(x),
            jnp.asarray(gates), capacity_factor=float(n_exp),  # no drops
        ))
        ref = _oracle(ws, x, gates, cap_per_bucket=10**9, n_exp=n_exp)
        np.testing.assert_allclose(got, ref, rtol=1e-10, atol=1e-12)

    def test_capacity_drops_pass_through(self, rng, mesh):
        n_exp = len(mesh.devices.flat)
        t, d = n_exp * 4, 8
        ws = rng.standard_normal((n_exp, d, d))
        x = rng.standard_normal((t, d))
        gates = np.full((t, n_exp), -10.0)
        gates[:, 0] = 10.0  # every token wants expert 0 -> guaranteed drops
        cf = 1.0
        t_loc = t // n_exp
        cap = max(1, int(np.ceil(t_loc * cf / n_exp)))
        got = np.asarray(expert_parallel_apply(
            _linear_expert, jnp.asarray(ws), jnp.asarray(x),
            jnp.asarray(gates), capacity_factor=cf,
        ))
        ref = _oracle(ws, x, gates, cap_per_bucket=cap, n_exp=n_exp)
        np.testing.assert_allclose(got, ref, rtol=1e-10, atol=1e-12)
        # And drops genuinely happened: some rows are identity passthrough.
        assert np.any(np.all(got == x, axis=1))

    def test_bad_shapes_raise(self, rng, mesh):
        n_exp = len(mesh.devices.flat)
        d = 4
        ws = jnp.asarray(rng.standard_normal((n_exp, d, d)))
        with pytest.raises(ValueError, match="divide"):
            expert_parallel_apply(_linear_expert, ws,
                                  jnp.zeros((n_exp + 1, d)),
                                  jnp.zeros((n_exp + 1, n_exp)))
        with pytest.raises(ValueError, match="gate_logits"):
            expert_parallel_apply(_linear_expert, ws,
                                  jnp.zeros((n_exp * 2, d)),
                                  jnp.zeros((n_exp * 2, n_exp + 1)))
        with pytest.raises(ValueError, match="leading axis"):
            expert_parallel_apply(
                _linear_expert,
                jnp.asarray(rng.standard_normal((3, d, d))),
                jnp.zeros((n_exp * 2, d)), jnp.zeros((n_exp * 2, n_exp)),
            )


class TestExpertFnContract:
    def test_expert_fn_receives_flat_token_batch(self, rng, mesh):
        # The documented contract: expert_fn sees (tokens, d), 2-D — a
        # per-token mean-subtraction must act over ALL arrived tokens, and
        # an ndim assert must hold (regression: it used to get (src, cap, d)).
        n_exp = len(mesh.devices.flat)
        d = 4
        seen_ndim = []

        def expert(w, xx):
            seen_ndim.append(xx.ndim)
            assert xx.ndim == 2
            return xx @ w

        ws = jnp.asarray(rng.standard_normal((n_exp, d, d)))
        x = jnp.asarray(rng.standard_normal((n_exp * 2, d)))
        g = jnp.asarray(rng.standard_normal((n_exp * 2, n_exp)))
        expert_parallel_apply(expert, ws, x, g, capacity_factor=float(n_exp))
        assert seen_ndim and all(nd == 2 for nd in seen_ndim)

    def test_stable_fn_reuses_compile(self, rng, mesh):
        n_exp = len(mesh.devices.flat)
        d = 4
        ws = jnp.asarray(rng.standard_normal((n_exp, d, d)))
        x = jnp.asarray(rng.standard_normal((n_exp * 2, d)))
        g = jnp.asarray(rng.standard_normal((n_exp * 2, n_exp)))
        expert_parallel_apply(_linear_expert, ws, x, g)
        cache = _linear_expert.__dict__.get("_marlin_compiled")
        assert cache  # rides on the callable, not a module global
        n0 = len(cache)
        expert_parallel_apply(_linear_expert, ws, x, g)
        assert len(cache) == n0  # same compiled program reused


class TestExpertTraining:
    def test_gradients_match_dense_oracle(self, rng, mesh):
        # Reverse-mode flows through the bucketing scatter, both
        # all_to_alls, and the gate-prob scaling: grads for expert params,
        # tokens, AND gates match the dense top-1 oracle exactly (the gate
        # gradient is the standard prob-factor MoE router signal).
        import jax

        n_exp = len(mesh.devices.flat)
        d, t = 6, 3 * n_exp
        ws = jnp.asarray(rng.standard_normal((n_exp, d, d)) * 0.4)
        x = jnp.asarray(rng.standard_normal((t, d)))
        g = jnp.asarray(rng.standard_normal((t, n_exp)))

        def loss_ep(ws, x, g):
            return jnp.sum(expert_parallel_apply(
                _linear_expert, ws, x, g, capacity_factor=float(n_exp)) ** 2)

        def loss_dense(ws, x, g):
            probs = jax.nn.softmax(g, axis=-1)
            top = jnp.argmax(g, axis=-1)
            out = jnp.einsum("td,tde->te", x, ws[top]) * jnp.take_along_axis(
                probs, top[:, None], 1)
            return jnp.sum(out ** 2)

        ge = jax.jit(jax.grad(loss_ep, argnums=(0, 1, 2)))(ws, x, g)
        gd = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(ws, x, g)
        for a, b in zip(ge, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-9, atol=1e-12)
