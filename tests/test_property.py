"""Property-style randomized sweeps: distributed ops vs the NumPy oracle over
random shapes, engines, re-blocking plans, and larger decompositions —
coverage the reference never had (SURVEY.md §4: "no property-based tests").

Each case is seeded from the test id (see conftest ``rng``), so failures
reproduce exactly.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from marlin_tpu.matrix.block import BlockMatrix
from marlin_tpu.matrix.dense import DenseVecMatrix
from marlin_tpu.parallel import summa


def _rand_shape(rng, lo=1, hi=40):
    return int(rng.integers(lo, hi + 1))


class TestGemmSweep:
    def test_random_shapes_all_engines(self, rng):
        """Random (m, k, n) triples through every engine vs the oracle —
        including degenerate 1-sized dims the fixed fixtures never hit.
        Cannon needs a square mesh (the default (4,2) silently rewrites it
        to summa), so it runs on an explicit 2x2 submesh."""
        import jax

        import marlin_tpu as mt

        square = mt.create_mesh((2, 2), devices=jax.devices()[:4])
        for trial in range(8):
            m, k, n = (_rand_shape(rng) for _ in range(3))
            a = rng.standard_normal((m, k))
            b = rng.standard_normal((k, n))
            oracle = a @ b
            for engine, mesh in (
                ("summa", None),
                ("cannon", square),
                ("gspmd", None),
            ):
                out = summa.matmul(a, b, mesh=mesh, engine=engine)
                np.testing.assert_allclose(
                    np.asarray(out), oracle, rtol=1e-10, atol=1e-10,
                    err_msg=f"engine={engine} shape=({m},{k},{n}) trial={trial}",
                )

    def test_random_shapes_auto_dispatch(self, rng):
        for trial in range(6):
            m, k, n = (_rand_shape(rng, 2, 50) for _ in range(3))
            a = rng.standard_normal((m, k))
            b = rng.standard_normal((k, n))
            out = DenseVecMatrix(a).multiply(DenseVecMatrix(b))
            np.testing.assert_allclose(
                out.to_numpy(), a @ b, rtol=1e-10, atol=1e-10,
                err_msg=f"shape=({m},{k},{n}) trial={trial}",
            )

    def test_random_grid_splits(self, rng):
        """Random explicit (pm, pk, pn) splits — the multiply(that, (m,k,n))
        overload. Grids are drawn from the set that actually reaches the 3-D
        psum engine (pk >= 2, product <= 8 devices); pk == 1 and oversized
        grids fall back to 2-D and are covered elsewhere."""
        valid = [
            (pm, pk, pn)
            for pm in (1, 2, 4)
            for pk in (2, 4)
            for pn in (1, 2)
            if pm * pk * pn <= 8
        ]
        a = rng.standard_normal((24, 36))
        b = rng.standard_normal((36, 16))
        for grid in rng.permutation(len(valid))[:6]:
            grid = valid[int(grid)]
            out = DenseVecMatrix(a).multiply(DenseVecMatrix(b), mode=grid)
            np.testing.assert_allclose(
                out.to_numpy(), a @ b, rtol=1e-10, atol=1e-10,
                err_msg=f"grid={grid}",
            )


class TestReblockRoundTrip:
    def test_random_regrid_preserves_values(self, rng):
        rows, cols = 37, 29  # deliberately prime: every grid is uneven
        arr = rng.standard_normal((rows, cols))
        mat = BlockMatrix(arr, blks_by_row=3, blks_by_col=2)
        for _ in range(6):
            r = int(rng.integers(1, 8))
            c = int(rng.integers(1, 8))
            mat = mat.to_block_matrix(r, c)
            assert (mat.blks_by_row, mat.blks_by_col) == (r, c)
            np.testing.assert_allclose(mat.to_numpy(), arr, rtol=1e-12)

    def test_dense_block_dense_cycle(self, rng):
        arr = rng.standard_normal((23, 31))
        m = DenseVecMatrix(arr)
        for _ in range(4):
            r = int(rng.integers(1, 6))
            c = int(rng.integers(1, 6))
            m = m.to_block_matrix(r, c).to_dense_vec_matrix()
            np.testing.assert_allclose(m.to_numpy(), arr, rtol=1e-12)

    def test_slice_cbind_identity(self, rng):
        """Slicing a matrix apart and c_bind-ing it back is the identity."""
        arr = rng.standard_normal((12, 20))
        m = DenseVecMatrix(arr)
        for _ in range(5):
            cut = int(rng.integers(1, 19))
            left = m.slice_by_column(0, cut - 1)  # reference bounds: inclusive
            right = m.slice_by_column(cut, 19)
            glued = left.c_bind(right)
            np.testing.assert_allclose(glued.to_numpy(), arr, rtol=1e-12)


class TestLargerDecompositions:
    """The fixed fixtures stop at ~24x24; these stress multi-panel dist paths."""

    def test_lu_dist_multi_panel(self, rng):
        n = 150
        a = rng.standard_normal((n, n)) + n * np.eye(n)
        mat = DenseVecMatrix(a)
        import marlin_tpu as mt

        from marlin_tpu.linalg import unpack_lu

        with mt.config_override(lu_base_size=32):
            packed, perm = mat.lu_decompose(mode="dist")
            l, u = unpack_lu(packed.to_numpy())
            np.testing.assert_allclose(l @ u, a[perm], rtol=1e-8, atol=1e-8)

    def test_cholesky_dist_multi_panel(self, rng):
        n = 120
        g = rng.standard_normal((n, n))
        a = g @ g.T + n * np.eye(n)
        import marlin_tpu as mt

        with mt.config_override(cholesky_base_size=32):
            l = DenseVecMatrix(a).cholesky_decompose(mode="dist")
            np.testing.assert_allclose(
                l.to_numpy() @ l.to_numpy().T, a, rtol=1e-8, atol=1e-6
            )

    def test_inverse_multi_panel(self, rng):
        n = 96
        a = rng.standard_normal((n, n)) + n * np.eye(n)
        import marlin_tpu as mt

        with mt.config_override(inverse_base_size=32):
            inv = DenseVecMatrix(a).inverse(mode="dist")
            np.testing.assert_allclose(
                inv.to_numpy() @ a, np.eye(n), atol=1e-7
            )

    def test_svd_wide_and_tall(self, rng):
        # The Gramian is over columns, so wide inputs (rows < cols) work
        # directly — no transpose-first needed.
        for shape in [(80, 30), (30, 80)]:
            arr = rng.standard_normal(shape)
            svd = DenseVecMatrix(arr).compute_svd(6, compute_u=True)
            s_ref = np.linalg.svd(arr, compute_uv=False)[:6]
            np.testing.assert_allclose(svd.s, s_ref, rtol=1e-6)
            recon = (svd.u.to_numpy() * svd.s) @ svd.v.T
            proj = np.linalg.svd(arr, full_matrices=False)
            best6 = (proj[0][:, :6] * proj[1][:6]) @ proj[2][:6]
            np.testing.assert_allclose(recon, best6, atol=1e-5)


class TestParallelEnginesPropertySweep:
    """Randomized shape sweeps for the round-2 engines (gpipe, expert,
    streaming ingestion) — the same sweep-the-shapes style as above."""

    def test_gpipe_random_shapes(self, rng):
        from marlin_tpu.parallel.pipeline import gpipe

        n_stages = 8
        for _ in range(4):
            d = int(rng.integers(3, 20))
            micro = int(rng.choice([2, 4, 8, 16]))
            batch = micro * int(rng.integers(1, 5))
            ws = rng.standard_normal((n_stages, d, d)) * 0.3
            x = rng.standard_normal((batch, d))
            got = np.asarray(gpipe(
                lambda w, xx: jnp.tanh(xx @ w), jnp.asarray(ws),
                jnp.asarray(x), n_microbatches=micro,
            ))
            ref = x.copy()
            for i in range(n_stages):
                ref = np.tanh(ref @ ws[i])
            np.testing.assert_allclose(got, ref, rtol=1e-9, atol=1e-9)

    def test_expert_random_gates(self, rng):
        from marlin_tpu.parallel.expert import expert_parallel_apply

        n_exp = 8
        for _ in range(4):
            d = int(rng.integers(2, 24))
            t = n_exp * int(rng.integers(1, 6))
            ws = rng.standard_normal((n_exp, d, d)) * 0.4
            x = rng.standard_normal((t, d))
            gates = rng.standard_normal((t, n_exp))
            got = np.asarray(expert_parallel_apply(
                lambda w, xx: xx @ w, jnp.asarray(ws), jnp.asarray(x),
                jnp.asarray(gates), capacity_factor=float(n_exp),
            ))
            probs = np.exp(gates - gates.max(1, keepdims=True))
            probs /= probs.sum(1, keepdims=True)
            top = gates.argmax(1)
            ref = np.stack([x[i] @ ws[top[i]] * probs[i, top[i]]
                            for i in range(t)])
            np.testing.assert_allclose(got, ref, rtol=1e-9, atol=1e-10)

    def test_streaming_loader_random_shapes(self, rng, tmp_path):
        from marlin_tpu.utils import io as mio

        for trial in range(3):
            m = int(rng.integers(3, 60))
            n = int(rng.integers(1, 12))
            a = rng.standard_normal((m, n))
            path = str(tmp_path / f"mat{trial}")
            mio.save_dense_matrix(DenseVecMatrix(a), path)
            got = mio.load_dense_matrix_streaming(path)
            np.testing.assert_allclose(got.to_numpy(), a)
            assert got.shape == (m, n)
