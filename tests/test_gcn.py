"""GCN model family on the distributed sparse engine.

Golden pattern: the distributed model vs a dense-adjacency NumPy/JAX oracle
with identical params — forward exact, gradients exact — plus learning on a
synthetic two-community graph."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marlin_tpu.matrix.dist_sparse import DistSparseVecMatrix, spmm
from marlin_tpu.models.gcn import (
    GCNConfig,
    accuracy,
    forward,
    init_params,
    loss_fn,
    normalize_adjacency,
    train_step,
)


def _two_communities(rng, n=48, p_in=0.5, p_out=0.05):
    """Random graph with two dense blocks; labels = community."""
    labels = np.arange(n) % 2
    prob = np.where(labels[:, None] == labels[None, :], p_in, p_out)
    adj = rng.random((n, n)) < prob
    adj = np.triu(adj, 1)
    r, c = np.nonzero(adj)
    return r, c, labels


def _dense_a_hat(r, c, n):
    a = np.zeros((n, n))
    a[r, c] = 1.0
    a = np.maximum(a, a.T)
    np.fill_diagonal(a, 1.0)
    d = a.sum(1)
    return a / np.sqrt(np.outer(d, d))


class TestSpmmGrad:
    def test_gradient_is_transpose_product(self, rng):
        m, k, n = 40, 48, 12
        mask = rng.random((m, k)) < 0.2
        r, c = np.nonzero(mask)
        v = rng.standard_normal(r.shape[0])
        a = DistSparseVecMatrix.from_coo(r, c, v, (m, k))
        ad = np.zeros((m, k))
        np.add.at(ad, (r, c), v)
        b = jnp.asarray(rng.standard_normal((k, n)))
        w = jnp.asarray(rng.standard_normal((m, n)))
        for g in (
            jax.grad(lambda b: jnp.sum(spmm(a, b) * w))(b),
            jax.jit(jax.grad(lambda b: jnp.sum(spmm(a, b) * w)))(b),
        ):
            np.testing.assert_allclose(
                np.asarray(g), ad.T @ np.asarray(w), rtol=1e-8, atol=1e-10)

    def test_transpose_cached_both_ways(self, rng):
        r, c = np.nonzero(rng.random((16, 24)) < 0.3)
        a = DistSparseVecMatrix.from_coo(
            r, c, np.ones(len(r)), (16, 24))
        t = a.transpose()
        assert t.shape == (24, 16)
        assert t.transpose() is a and a.T is t
        np.testing.assert_allclose(t.to_numpy(), a.to_numpy().T)

    def test_dimension_mismatch(self, rng):
        r, c = np.nonzero(rng.random((8, 8)) < 0.5)
        a = DistSparseVecMatrix.from_coo(r, c, np.ones(len(r)), (8, 8))
        with pytest.raises(ValueError):
            spmm(a, jnp.zeros((9, 4)))


class TestGCN:
    def test_forward_matches_dense_oracle(self, rng):
        n = 40
        r, c, labels = _two_communities(rng, n)
        cfg = GCNConfig(n_features=8, n_hidden=12, n_classes=2)
        a_hat = normalize_adjacency(r, c, n)
        np.testing.assert_allclose(
            a_hat.to_numpy(), _dense_a_hat(r, c, n), rtol=1e-10, atol=1e-12)
        params = init_params(cfg, seed=0)
        x = jnp.asarray(rng.standard_normal((n, 8)), jnp.float32)
        got = forward(params, a_hat, x)
        ah = jnp.asarray(_dense_a_hat(r, c, n), jnp.float32)
        h = ah @ (x @ params[0]["w"] + params[0]["b"])
        h = jax.nn.relu(h)
        ref = ah @ (h @ params[1]["w"] + params[1]["b"])
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_gradients_match_dense_oracle(self, rng):
        n = 32
        r, c, labels = _two_communities(rng, n)
        cfg = GCNConfig(n_features=6, n_hidden=8, n_classes=2)
        a_hat = normalize_adjacency(r, c, n)
        params = init_params(cfg, seed=1)
        x = jnp.asarray(rng.standard_normal((n, 6)), jnp.float32)
        y = jnp.asarray(labels, jnp.int32)
        mask = jnp.ones((n,), bool)
        g_dist = jax.grad(loss_fn)(params, a_hat, x, y, mask)

        ah = jnp.asarray(_dense_a_hat(r, c, n), jnp.float32)

        def dense_loss(params):
            h = x
            for i, l in enumerate(params):
                h = ah @ (h @ l["w"] + l["b"])
                if i + 1 < len(params):
                    h = jax.nn.relu(h)
            logp = jax.nn.log_softmax(h, axis=-1)
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1)[:, 0])

        g_ref = jax.grad(dense_loss)(params)
        for a_, b_ in zip(jax.tree.leaves(g_dist), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(
                np.asarray(a_), np.asarray(b_), rtol=2e-4, atol=1e-5)

    def test_learns_two_communities(self, rng):
        n = 64
        r, c, labels = _two_communities(rng, n)
        cfg = GCNConfig(n_features=4, n_hidden=16, n_classes=2)
        a_hat = normalize_adjacency(r, c, n)
        params = init_params(cfg, seed=2)
        # Weakly informative features: a community signal buried in noise a
        # single node can't classify reliably — neighborhood smoothing
        # through A_hat (the thing under test) recovers it.
        sig = np.eye(2)[labels]
        x = jnp.asarray(
            np.concatenate([sig, np.zeros((n, 2))], axis=1)
            + 2.0 * rng.standard_normal((n, 4)),
            jnp.float32,
        )
        y = jnp.asarray(labels, jnp.int32)
        # Semi-supervised: label a random 1/4 of the nodes (a strided mask
        # would hit a single community — labels alternate), test the rest.
        mask = np.zeros(n, bool)
        mask[rng.choice(n, n // 4, replace=False)] = True
        train_mask = jnp.asarray(mask)
        step = jax.jit(
            lambda p, x, y, m: train_step(p, a_hat, x, y, m, lr=0.5))
        l0, params = step(params, x, y, train_mask)
        lN = l0
        for _ in range(60):
            lN, params = step(params, x, y, train_mask)
        assert float(lN) < 0.5 * float(l0)
        test_acc = accuracy(params, a_hat, x, y, ~np.asarray(mask))
        assert test_acc > 0.8, test_acc
