"""Worker process for the 2-process ``jax.distributed`` test.

The reference's multi-node substrate is Spark's driver/executor RPC + shuffle
service (SURVEY.md §2.8); ours is ``mesh.init_distributed`` →
``jax.distributed.initialize``. This worker is launched twice (process_id 0/1)
by ``tests/test_multihost.py``; each process owns 4 virtual CPU devices, and
the two build ONE spanning 8-device mesh. Everything below then runs on a mesh
whose collectives genuinely cross a process boundary — the closest CPU-only
analogue of a DCN-spanning TPU pod:

* sharded-type GEMM through the full auto-dispatch ``multiply`` path,
* the explicit shard_map SUMMA engine,
* a cross-process ``psum`` (tree-reduce analogue),
* orbax checkpoint save + restore INTO the spanning mesh (each process
  writes/reads only its addressable shards).

Prints ``MULTIHOST_OK pid=<i>`` on success; any assertion kills the process
and fails the parent test.
"""

import os
import sys


def main() -> None:
    pid = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = int(sys.argv[3])
    ckpt_dir = sys.argv[4]

    # 8 // nproc virtual CPU devices per process -> 8 global (2 or 4
    # processes). Must be set before the backend initializes; overrides any
    # value inherited from the parent (the pytest conftest forces 8
    # in-process). A nproc that doesn't divide 8 would silently yield
    # fewer than 8 global devices and break the fixed-8 mesh assumption
    # downstream — fail loudly instead.
    assert 8 % nproc == 0, f"nproc {nproc} must divide the 8-device mesh"
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={8 // nproc}")

    import jax

    # sitecustomize pins the axon TPU platform via jax.config; override back.
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    import marlin_tpu as mt

    mt.init_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    assert jax.process_count() == nproc, jax.process_count()
    n_local = len(jax.local_devices())
    n_global = len(jax.devices())
    assert n_global == nproc * n_local, (n_global, n_local)

    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from marlin_tpu import mesh as mesh_mod

    mesh = mt.create_mesh()  # spans both processes: (4, 2) over 8 devices
    mt.set_default_mesh(mesh)
    spanning = {d.process_index for d in mesh.devices.flat}
    assert spanning == set(range(nproc)), spanning

    rng = np.random.default_rng(0)  # identical stream on every process

    # --- cross-process psum: the treeReduce analogue ----------------------
    x = jnp.arange(float(n_global))
    xs = jax.device_put(x, mesh_mod.vector_sharding(mesh))
    total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(xs)
    # The result is replicated over the spanning mesh: every process reads its
    # own addressable copy (the "collect to driver" of a tree reduce).
    np.testing.assert_allclose(
        np.asarray(total.addressable_shards[0].data),
        n_global * (n_global - 1) / 2.0,
    )

    # --- explicit SUMMA engine over the spanning mesh ---------------------
    from marlin_tpu.parallel import summa

    a = rng.standard_normal((48, 40))
    b = rng.standard_normal((40, 24))
    out = summa.matmul(jnp.asarray(a), jnp.asarray(b), mesh=mesh, engine="summa")
    out_h = multihost_utils.process_allgather(out, tiled=True)
    np.testing.assert_allclose(out_h, a @ b, rtol=1e-10, atol=1e-10)

    # --- sharded-type GEMM (the SUMMA arm of the dispatch) ----------------
    a2 = rng.standard_normal((32, 24))
    b2 = rng.standard_normal((24, 16))
    am = mt.DenseVecMatrix(a2, mesh=mesh)
    bm = mt.DenseVecMatrix(b2, mesh=mesh)
    cm = am.multiply(bm, mode="summa")
    c_h = multihost_utils.process_allgather(cm.data, tiled=True)
    np.testing.assert_allclose(
        c_h[: cm.shape[0], : cm.shape[1]], a2 @ b2, rtol=1e-10, atol=1e-10
    )

    # --- checkpoint save/restore across the spanning mesh -----------------
    from marlin_tpu.utils import checkpoint as ckpt

    path = os.path.join(ckpt_dir, "mat")
    ckpt.save_matrix(cm, path)
    restored = ckpt.load_matrix(path, mesh=mesh)
    assert restored.shape == cm.shape
    r_h = multihost_utils.process_allgather(restored.data, tiled=True)
    np.testing.assert_allclose(r_h, c_h)

    def fetch(x):
        if x.is_fully_replicated:
            return np.asarray(x)
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))

    # --- dist LU factor across the process boundary -----------------------
    # The panel-pivoted single-jit sweep on a row-sharded spanning array:
    # the Schur GEMM and pivot gathers run SPMD over the DCN-analogue mesh
    # (VERDICT r02 item 7; match DenseVecMatrix.scala:283-461).
    from marlin_tpu.linalg.lu import lu_factor_array, unpack_lu

    a_lu = rng.standard_normal((64, 64))
    a_dev = jax.device_put(jnp.asarray(a_lu), mesh_mod.row_sharding(mesh))
    with mt.config_override(lu_base_size=16):
        packed, perm = lu_factor_array(a_dev, mode="dist")
    l, u = unpack_lu(np.asarray(fetch(packed), np.float64))
    np.testing.assert_allclose(a_lu[perm], l @ u, rtol=1e-8, atol=1e-8)

    # --- ALS half-step across the spanning mesh ---------------------------
    # One updateFeatures call (users from products, ALSHelp.scala:263) with
    # the product factors row-sharded over the spanning mesh; the result
    # must match the same update computed process-locally.
    from marlin_tpu.ml.als import _update_side

    m_u, n_p, rank = 32, 24, 4
    nr = 200
    r_u = jnp.asarray(rng.integers(0, m_u, nr))
    r_p = jnp.asarray(rng.integers(0, n_p, nr))
    r_v = jnp.asarray(rng.random(nr))
    prod_h = jnp.asarray(rng.standard_normal((n_p, rank)))
    prod_d = jax.device_put(prod_h, mesh_mod.row_sharding(mesh))
    users_span = _update_side(prod_d, r_p, r_u, r_v, m_u, 0.1, 1.0, False,
                              rank)
    users_local = _update_side(prod_h, r_p, r_u, r_v, m_u, 0.1, 1.0, False,
                               rank)
    np.testing.assert_allclose(fetch(users_span), fetch(users_local),
                               rtol=1e-8, atol=1e-10)

    # --- transformer dp train step across the process boundary ------------
    from marlin_tpu.models import TransformerConfig, init_params, train_step

    cfg_t = TransformerConfig(vocab=128, d_model=32, n_heads=2, n_layers=1,
                              d_ff=64, max_len=16)
    params = init_params(cfg_t, seed=0)
    tok_h = rng.integers(0, 128, (8, 16))
    dp = NamedSharding(mesh, P(tuple(mesh.axis_names), None))
    tokens = jax.device_put(jnp.asarray(tok_h), dp)
    targets = jax.device_put(jnp.asarray(np.roll(tok_h, -1, axis=1)), dp)
    step = jax.jit(train_step, static_argnames="cfg")
    loss, new_params = step(params, tokens, targets, cfg=cfg_t)
    loss_v = float(fetch(loss))
    assert np.isfinite(loss_v), loss_v

    print(f"MULTIHOST_OK pid={pid} local={n_local} global={n_global}", flush=True)


if __name__ == "__main__":
    main()
