"""Worker process for the 2-process ``jax.distributed`` test.

The reference's multi-node substrate is Spark's driver/executor RPC + shuffle
service (SURVEY.md §2.8); ours is ``mesh.init_distributed`` →
``jax.distributed.initialize``. This worker is launched twice (process_id 0/1)
by ``tests/test_multihost.py``; each process owns 4 virtual CPU devices, and
the two build ONE spanning 8-device mesh. Everything below then runs on a mesh
whose collectives genuinely cross a process boundary — the closest CPU-only
analogue of a DCN-spanning TPU pod:

* sharded-type GEMM through the full auto-dispatch ``multiply`` path,
* the explicit shard_map SUMMA engine,
* a cross-process ``psum`` (tree-reduce analogue),
* orbax checkpoint save + restore INTO the spanning mesh (each process
  writes/reads only its addressable shards).

Prints ``MULTIHOST_OK pid=<i>`` on success; any assertion kills the process
and fails the parent test.
"""

import os
import sys


def main() -> None:
    pid = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = int(sys.argv[3])
    ckpt_dir = sys.argv[4]

    # 4 virtual CPU devices per process -> 8 global. Must be set before the
    # backend initializes; overrides any value inherited from the parent
    # (the pytest conftest forces 8 in-process).
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

    import jax

    # sitecustomize pins the axon TPU platform via jax.config; override back.
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    import marlin_tpu as mt

    mt.init_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    assert jax.process_count() == nproc, jax.process_count()
    n_local = len(jax.local_devices())
    n_global = len(jax.devices())
    assert n_global == nproc * n_local, (n_global, n_local)

    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from marlin_tpu import mesh as mesh_mod

    mesh = mt.create_mesh()  # spans both processes: (4, 2) over 8 devices
    mt.set_default_mesh(mesh)
    spanning = {d.process_index for d in mesh.devices.flat}
    assert spanning == set(range(nproc)), spanning

    rng = np.random.default_rng(0)  # identical stream on every process

    # --- cross-process psum: the treeReduce analogue ----------------------
    x = jnp.arange(float(n_global))
    xs = jax.device_put(x, mesh_mod.vector_sharding(mesh))
    total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(xs)
    # The result is replicated over the spanning mesh: every process reads its
    # own addressable copy (the "collect to driver" of a tree reduce).
    np.testing.assert_allclose(
        np.asarray(total.addressable_shards[0].data),
        n_global * (n_global - 1) / 2.0,
    )

    # --- explicit SUMMA engine over the spanning mesh ---------------------
    from marlin_tpu.parallel import summa

    a = rng.standard_normal((48, 40))
    b = rng.standard_normal((40, 24))
    out = summa.matmul(jnp.asarray(a), jnp.asarray(b), mesh=mesh, engine="summa")
    out_h = multihost_utils.process_allgather(out, tiled=True)
    np.testing.assert_allclose(out_h, a @ b, rtol=1e-10, atol=1e-10)

    # --- sharded-type GEMM (the SUMMA arm of the dispatch) ----------------
    a2 = rng.standard_normal((32, 24))
    b2 = rng.standard_normal((24, 16))
    am = mt.DenseVecMatrix(a2, mesh=mesh)
    bm = mt.DenseVecMatrix(b2, mesh=mesh)
    cm = am.multiply(bm, mode="summa")
    c_h = multihost_utils.process_allgather(cm.data, tiled=True)
    np.testing.assert_allclose(
        c_h[: cm.shape[0], : cm.shape[1]], a2 @ b2, rtol=1e-10, atol=1e-10
    )

    # --- checkpoint save/restore across the spanning mesh -----------------
    from marlin_tpu.utils import checkpoint as ckpt

    path = os.path.join(ckpt_dir, "mat")
    ckpt.save_matrix(cm, path)
    restored = ckpt.load_matrix(path, mesh=mesh)
    assert restored.shape == cm.shape
    r_h = multihost_utils.process_allgather(restored.data, tiled=True)
    np.testing.assert_allclose(r_h, c_h)

    print(f"MULTIHOST_OK pid={pid} local={n_local} global={n_global}", flush=True)


if __name__ == "__main__":
    main()
