"""Fleet tier tests (docs/fleet.md): prefix-affinity routing, failover,
drain-under-load byte-exactness, aggregated observability.

The subprocess tests use the ``fleet_factory`` fixture (conftest.py):
N REAL replica subprocesses — each a full serving/server.py stack on an
ephemeral port with deterministic seeds — behind an in-process front
door, torn down hard even when the test fails. Byte-exactness is
checked against an IN-PROCESS golden engine built with the same
cfg/seed and the router-assigned request ids: engine output is
f(prompt, steps, seed, request_id), so fleet responses must equal the
golden regardless of which replica (or how many failovers) served them.
"""

import http.client
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from marlin_tpu.fleet import FleetConfig, PrefixAffinityRouter
from marlin_tpu.fleet.router import NoHealthyReplica
from marlin_tpu.fleet.server import inject_replica_label

HOST = "127.0.0.1"


# -- HTTP helpers ------------------------------------------------------


def _post(port, body, headers=None, timeout=60.0):
    conn = http.client.HTTPConnection(HOST, port, timeout=timeout)
    try:
        conn.request("POST", "/v1/generate", json.dumps(body).encode(),
                     headers or {})
        resp = conn.getresponse()
        data = resp.read()
        return resp.status, data, dict(resp.getheaders())
    finally:
        conn.close()


def _get(port, path, timeout=30.0):
    conn = http.client.HTTPConnection(HOST, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _gen(port, prompt, steps, **extra):
    """Blocking generate; returns (request_id, tokens, replica, hdrs)."""
    st, data, hdrs = _post(port, {"prompt": list(prompt),
                                  "steps": steps, **extra})
    assert st == 200, (st, data)
    obj = json.loads(data)
    return (obj["request_id"], obj["tokens"],
            int(hdrs["X-Fleet-Replica"]), hdrs)


def _gen_stream(port, prompt, steps):
    """SSE generate; returns (request_id, tokens, replica)."""
    conn = http.client.HTTPConnection(HOST, port, timeout=60.0)
    try:
        conn.request("POST", "/v1/generate",
                     json.dumps({"prompt": list(prompt), "steps": steps,
                                 "stream": True}).encode())
        resp = conn.getresponse()
        assert resp.status == 200
        replica = int(resp.getheader("X-Fleet-Replica"))
        raw = resp.read().decode()
    finally:
        conn.close()
    tokens, rid = [], None
    for ev in raw.split("\n\n"):
        if ev.startswith("data: "):
            d = json.loads(ev[len("data: "):])
            tokens += d.get("tokens", [])
            if d.get("done"):
                assert d.get("status") == "done", d
                rid = d["request_id"]
    return rid, tokens, replica


# -- in-process golden -------------------------------------------------


def _golden_tokens(jobs, temperature=0.0, kv_pages=None):
    """Run (request_id, prompt, steps) jobs on an in-process engine
    with the fleet's cfg/seed; returns {request_id: tokens}. The fleet
    must match these bytes exactly."""
    from marlin_tpu.models import TransformerConfig, init_params
    from marlin_tpu.serving.engine import ServingEngine
    from marlin_tpu.serving.frontend import EngineFrontend

    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=2,
                            n_layers=1, d_ff=128, max_len=128,
                            dtype="float32")
    params = init_params(cfg, seed=0)
    kw = {"kv_pages": kv_pages} if kv_pages is not None else {}
    engine = ServingEngine(params, cfg, batch=4, round_steps=4,
                           temperature=temperature, seed=0, **kw)
    fe = EngineFrontend(engine).start()
    try:
        handles = [(rid, fe.submit(np.asarray(p, np.int32), s,
                                   request_id=rid))
                   for rid, p, s in jobs]
        out = {}
        for rid, h in handles:
            req = h.result(120.0)
            assert req.status == "done"
            out[rid] = np.asarray(req.tokens).tolist()
        return out
    finally:
        fe.stop()


# -- router unit tests (no subprocesses) -------------------------------


class _StubReplica:
    def __init__(self, index, healthy=True):
        self.index = index
        self.healthy = healthy
        self.port = None


class _Reg:
    """Minimal metrics stand-in for router unit tests."""

    class _C:
        def inc(self, by=1.0):
            pass

    def counter(self, name, help="", **labels):
        return self._C()


def _router(n=2, healthy=None, **cfg_kw):
    cfg = FleetConfig(n_replicas=n, **cfg_kw)
    reps = [_StubReplica(i, healthy=(healthy is None or i in healthy))
            for i in range(n)]
    return PrefixAffinityRouter(reps, cfg, _Reg())


class TestRouterUnit:
    def test_affinity_hit_sticks_to_replica(self):
        r = _router()
        p = np.arange(32, dtype=np.int32)
        first = r.route(p)
        r.release(first)
        for _ in range(4):
            d = r.route(p)
            r.release(d)
            assert d.replica_index == first.replica_index
            assert d.policy == "affinity"
            assert d.hit_depth == 32

    def test_short_prompt_never_affine(self):
        r = _router()
        d = r.route(np.arange(8, dtype=np.int32))  # < GRAIN
        assert d.policy == "fallback"
        r.release(d)

    def test_fallback_spreads_when_idle(self):
        r = _router()
        seen = set()
        for k in range(2):
            d = r.route(np.arange(40 + k * 50, 40 + k * 50 + 16,
                                  dtype=np.int32))
            r.release(d)
            seen.add(d.replica_index)
        assert seen == {0, 1}  # routed-count tie-break round-robins

    def test_imbalance_overrides_affinity(self):
        r = _router(affinity_max_imbalance=1)
        p = np.arange(32, dtype=np.int32)
        first = r.route(p)  # stays outstanding
        second = r.route(p)  # affinity: imbalance 1 vs 0 is tolerated
        assert second.policy == "affinity"
        assert second.replica_index == first.replica_index
        # Now 2 vs 0 outstanding: load trumps locality — the route
        # falls back to the idle peer (and re-points affinity there,
        # so later same-prefix routes may legitimately affine to it).
        third = r.route(p)
        assert third.policy == "fallback"
        assert third.replica_index != first.replica_index
        fourth = r.route(p)
        assert fourth.policy == "affinity"
        assert fourth.replica_index == third.replica_index
        for x in (first, second, third, fourth):
            r.release(x)

    def test_unhealthy_replica_skipped_and_none_raises(self):
        r = _router(healthy={1})
        d = r.route(np.arange(32, dtype=np.int32))
        assert d.replica_index == 1
        r.release(d)
        r.replicas[1].healthy = False
        with pytest.raises(NoHealthyReplica):
            r.route(np.arange(32, dtype=np.int32))

    def test_reassign_moves_outstanding_and_affinity(self):
        r = _router()
        p = np.arange(32, dtype=np.int32)
        d = r.route(p)
        old = d.replica_index
        new = 1 - old
        r.reassign(d, new, reason="connect")
        assert r.outstanding(old) == 0
        assert r.outstanding(new) == 1
        r.release(d)
        # Affinity now points at the replica that actually served it.
        d2 = r.route(p)
        assert d2.replica_index == new
        assert d2.policy == "affinity"
        r.release(d2)

    def test_path_lru_bounded(self):
        r = _router(affinity_paths=4)
        for k in range(10):
            d = r.route(np.arange(k * 100, k * 100 + 16,
                                  dtype=np.int32) % 1000)
            r.release(d)
        with r._lock:
            assert len(r._paths) <= 4

    def test_ids_monotonic_unique(self):
        r = _router()
        ids = []
        for k in range(6):
            d = r.route(np.arange(16, dtype=np.int32) + k)
            r.release(d)
            ids.append(d.request_id)
        assert ids == sorted(set(ids))


class TestMetricsAggregation:
    def test_inject_replica_label(self):
        text = ("# HELP serving_completed_total done\n"
                "# TYPE serving_completed_total counter\n"
                "serving_completed_total 7\n"
                'serving_http_responses_total{code="200"} 3\n'
                'serving_phase_seconds_bucket{phase="decode",'
                'le="0.1"} 2\n')
        out = inject_replica_label(text, 1)
        lines = out.splitlines()
        assert 'serving_completed_total{replica="1"} 7' in lines
        assert ('serving_http_responses_total{replica="1",'
                'code="200"} 3') in lines
        assert ('serving_phase_seconds_bucket{replica="1",'
                'phase="decode",le="0.1"} 2') in lines
        assert not any(ln.startswith("#") for ln in lines)


# -- subprocess fleet tests --------------------------------------------

# Two GRAIN-aligned prompt families: requests within a family share a
# 32-token prefix (two trie chunks), so affinity keeps a family on one
# replica while families spread across replicas.
_FAMILY_A = [list(range(1, 33)) + [40 + k] for k in range(4)]
_FAMILY_B = [list(range(33, 1, -1)) + [50 + k] for k in range(4)]


class TestFleetRouting:
    def test_affinity_metrics_and_exactness(self, fleet_factory):
        """One fleet, many assertions (a fleet spawn costs ~5 s):
        affinity keeps prefix families replica-local, distinct families
        spread, responses are byte-exact vs the in-process golden
        (sampled path — temperature > 0 makes the request-id contract
        load-bearing), streamed == blocking framing, ids are unique,
        the aggregated /metrics carries replica= labels, and a
        caller-supplied request_id is rejected."""
        server = fleet_factory(n_replicas=2, kv_pages=64,
                               temperature=0.7)
        port = server.port
        results = []  # (rid, prompt, steps, tokens)

        rid0, toks0, rep_a, hdrs = _gen(port, _FAMILY_A[0], 6)
        results.append((rid0, _FAMILY_A[0], 6, toks0))
        assert hdrs["X-Engine-Request-Id"] == str(rid0)
        # X-Request-Id echo: the caller's id comes back verbatim.
        st, data, hdrs2 = _post(port, {"prompt": _FAMILY_A[1],
                                       "steps": 5},
                                headers={"X-Request-Id": "cafe-1"})
        assert st == 200 and hdrs2["X-Request-Id"] == "cafe-1"
        obj = json.loads(data)
        results.append((obj["request_id"], _FAMILY_A[1], 5,
                        obj["tokens"]))
        assert int(hdrs2["X-Fleet-Replica"]) == rep_a  # affinity

        # The rest of family A sticks to rep_a; family B spreads away.
        for p in _FAMILY_A[2:]:
            rid, toks, rep, _ = _gen(port, p, 6)
            results.append((rid, p, 6, toks))
            assert rep == rep_a
        rid_b, toks_b, rep_b, _ = _gen(port, _FAMILY_B[0], 6)
        results.append((rid_b, _FAMILY_B[0], 6, toks_b))
        assert rep_b != rep_a
        for p in _FAMILY_B[1:3]:
            rid, toks, rep, _ = _gen(port, p, 6)
            results.append((rid, p, 6, toks))
            assert rep == rep_b

        # Streamed == blocking: same prompt/steps on the same replica
        # via affinity; a fresh id, so fresh (but deterministic) bytes.
        srid, stoks, srep = _gen_stream(port, _FAMILY_A[0], 6)
        results.append((srid, _FAMILY_A[0], 6, stoks))
        assert srep == rep_a

        ids = [r[0] for r in results]
        assert ids == sorted(set(ids)), "router ids must be unique"

        # Router-owned ids: explicit request_id is rejected up front.
        st, data, _ = _post(port, {"prompt": [1, 2, 3], "steps": 2,
                                   "request_id": 7})
        assert st == 400

        # Aggregated metrics: every replica's series under replica=.
        st, data = _get(port, "/metrics")
        assert st == 200
        text = data.decode()
        for rep in ("0", "1"):
            assert f'serving_completed_total{{replica="{rep}"}}' \
                in text, text[:2000]
        assert 'fleet_route_total{policy="affinity"}' in text
        completed = sum(
            float(ln.rsplit(" ", 1)[1])
            for ln in text.splitlines()
            if ln.startswith('serving_completed_total{replica='))
        assert completed == len(results)

        # Byte-exactness: the golden engine with the SAME ids must
        # reproduce every fleet response bit for bit.
        golden = _golden_tokens(
            [(rid, p, s) for rid, p, s, _ in results],
            temperature=0.7, kv_pages=64)
        for rid, _p, _s, toks in results:
            assert toks == golden[rid], f"request {rid} diverged"

    def test_drain_under_load_byte_exact(self, fleet_factory):
        """Drain + restart one replica mid-load: zero dropped requests,
        every response byte-exact vs the golden, the drained replica
        comes back healthy with a fresh incarnation runlog."""
        server = fleet_factory(n_replicas=2, kv_pages=64)
        sup = server.supervisor
        port = server.port
        # Warm affinity so a family owns each replica.
        rid, toks, rep_a, _ = _gen(port, _FAMILY_A[0], 4)
        results = [(rid, _FAMILY_A[0], 4, toks)]
        rid, toks, rep_b, _ = _gen(port, _FAMILY_B[0], 4)
        results.append((rid, _FAMILY_B[0], 4, toks))

        lock = threading.Lock()
        failures = []

        def worker(prompts, steps, stream):
            for p in prompts:
                try:
                    if stream:
                        out = _gen_stream(port, p, steps)[:2]
                    else:
                        out = _gen(port, p, steps)[:2]
                    with lock:
                        results.append((out[0], p, steps, out[1]))
                except Exception as e:  # noqa: BLE001 - collected
                    with lock:
                        failures.append(repr(e))

        threads = [
            threading.Thread(target=worker,
                             args=(_FAMILY_A * 2, 5, False)),
            threading.Thread(target=worker,
                             args=(_FAMILY_B * 2, 5, True)),
            threading.Thread(target=worker,
                             args=(list(reversed(_FAMILY_A)) * 2, 6,
                                   True)),
        ]
        for t in threads:
            t.start()
        # Mid-load: drain the replica that owns family A, then respawn
        # it — the drill the admin endpoint exists for.
        time.sleep(0.3)
        st, data, _ = _post_drain(port, rep_a, restart=True)
        assert st == 202, data
        for t in threads:
            t.join(180.0)
            assert not t.is_alive()
        assert not failures, failures

        # Zero drops: every submitted request came back 200 with
        # tokens, through routing, drain 503-replays, or refusals.
        assert len(results) == 2 + 8 + 8 + 8
        ids = [r[0] for r in results]
        assert len(ids) == len(set(ids))

        # The drained replica returns healthy on a fresh incarnation,
        # with a per-incarnation runlog alongside the original.
        deadline = time.monotonic() + 60.0
        r = sup.replicas[rep_a]
        while time.monotonic() < deadline and not (
                r.healthy and r.incarnation == 1):
            time.sleep(0.2)
        assert r.healthy and r.incarnation == 1
        import os
        d = sup.config.runlog_dir
        assert os.path.exists(
            os.path.join(d, f"replica{rep_a}.jsonl"))
        assert os.path.exists(
            os.path.join(d, f"replica{rep_a}.r1.jsonl"))

        golden = _golden_tokens(
            [(rid, p, s) for rid, p, s, _ in results], kv_pages=64)
        for rid, _p, _s, toks in results:
            assert toks == golden[rid], f"request {rid} diverged"

    def test_replica_death_rerouting_and_fail_closed(
            self, fleet_factory):
        """An env-armed fault plan crashes replica 0's engine on every
        decode round; with a zero in-process restart budget it fails
        closed, the router replays the affected submission to the
        healthy peer (client still sees 200 + correct bytes), the fleet
        supervisor kills + respawns it within ITS budget, and once that
        budget is spent the replica is permanently failed while the
        fleet keeps serving."""
        plan = json.dumps({"specs": [{
            "site": "decode_round", "action": "raise",
            "round_every": 1, "max_fires": 1000}]})
        server = fleet_factory(
            n_replicas=2,
            max_restarts=0,  # in-process: first crash fails closed
            replica_max_restarts=1,  # fleet: one respawn, then failed
            probe_interval_s=0.1, unready_probe_limit=3,
            replica_env=((0, "MARLIN_FAULT_PLAN", plan),))
        port = server.port
        sup = server.supervisor

        # Both replicas healthy at spawn (faults fire only under
        # traffic). Drive fresh prompts until the armed replica has
        # died, been respawned, died again, and failed permanently —
        # every response must still be a 200 served somewhere.
        results = []
        deadline = time.monotonic() + 90.0
        k = 0
        while time.monotonic() < deadline:
            if sup.replicas[0].state == "failed":
                break
            p = [((k * 7) + j) % 64 for j in range(16)]
            rid, toks, rep, _ = _gen(port, p, 3)
            results.append((rid, p, 3, toks, rep))
            k += 1
            time.sleep(0.1)
        assert sup.replicas[0].state == "failed", \
            sup.replicas[0].status()
        assert sup.replicas[0].incarnation == 1  # one respawn happened
        assert len(results) >= 2

        # Degraded but ready: quorum 1 is met by the survivor.
        st, _ = _get(port, "/readyz")
        assert st == 200
        rid, toks, rep, _ = _gen(port, list(range(20)), 3)
        assert rep == 1
        results.append((rid, list(range(20)), 3, toks, rep))

        # Replays were byte-exact: whatever replica answered, the bytes
        # match the golden for the router-assigned id.
        golden = _golden_tokens(
            [(rid, p, s) for rid, p, s, _t, _r in results])
        for rid, _p, _s, toks, _rep in results:
            assert toks == golden[rid], f"request {rid} diverged"

        status = sup.status()
        assert status["router"]["failovers"] >= 1
        # Aggregated metrics still expose the survivor + fleet gauges.
        st, data = _get(port, "/metrics")
        text = data.decode()
        assert 'fleet_replica_healthy{replica="0"} 0' in text
        assert 'fleet_replica_healthy{replica="1"} 1' in text
        assert 'fleet_replica_restarts_total{replica="0"}' in text


def _post_drain(port, index, restart=False):
    conn = http.client.HTTPConnection(HOST, port, timeout=30.0)
    try:
        q = "?restart=1" if restart else ""
        conn.request("POST", f"/fleet/drain/{index}{q}")
        resp = conn.getresponse()
        return resp.status, resp.read(), dict(resp.getheaders())
    finally:
        conn.close()


_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestFleetSchedHeaders:
    def test_front_door_forwards_class_headers(self, fleet_factory):
        """Scheduler fields at the fleet tier (docs/serving.md §8):
        X-Sched-Class / X-Tenant headers fill missing body fields (body
        wins), the front door counts admissions by class, and an
        unknown class comes back as the REPLICA's 400 through the proxy
        — the class table lives in the replicas, never the router."""
        server = fleet_factory(n_replicas=1, kv_pages=32, sched=True)
        port = server.port

        st, data, _ = _post(port, {"prompt": [1, 2, 3, 4], "steps": 3},
                            headers={"X-Sched-Class": "interactive",
                                     "X-Tenant": "acme"})
        assert st == 200, data
        assert json.loads(data)["status"] == "done"

        # Body field wins: the bogus header class must be ignored.
        st, data, _ = _post(port, {"prompt": [1, 2, 3, 4], "steps": 3,
                                   "sched_class": "batch",
                                   "tenant": "acme"},
                            headers={"X-Sched-Class": "gold"})
        assert st == 200, data

        # Unknown class: the replica's 400 is forwarded untouched.
        st, data, _ = _post(port, {"prompt": [1, 2, 3, 4], "steps": 3},
                            headers={"X-Sched-Class": "gold"})
        assert st == 400
        assert b"unknown scheduling class" in data

        st, data = _get(port, "/metrics")
        assert st == 200
        text = data.decode()
        assert 'fleet_requests_by_class_total{cls="interactive"} 1' \
            in text, text[:2000]
        assert 'fleet_requests_by_class_total{cls="batch"} 1' in text
        # The rejected "gold" request still counted at the front door
        # (the counter measures demand by class, not admissions).
        assert 'fleet_requests_by_class_total{cls="gold"} 1' in text


class TestFleetBenchSmoke:
    def test_bench_fleet_line_and_slo_gate(self, tmp_path):
        """`bench.py --config fleet` end to end at the default knobs:
        the artifact line must show the MODELED capacity scaling >= the
        committed 3.0x floor (per-replica decode-iters deltas — see
        docs/fleet.md section bench for why raw wall-clock is ungated
        on 1-core CI hosts), byte-exact responses including across the
        mid-run drain/restart, zero steady-state recompiles, affinity
        hit-rate parity with the single-replica arm, and a clean fleet
        runlog merge — then pass tools/slo_check.py against the
        committed baseline's fleet block (the tier-1 form of the SLO
        gate)."""
        env = dict(os.environ, BENCH_FORCE_CPU="1", BENCH_RETRIES="1")
        r = subprocess.run(
            [sys.executable, "bench.py", "--config", "fleet"],
            capture_output=True, text=True, timeout=420, env=env,
            cwd=_REPO)
        assert r.returncode == 0, r.stderr[-800:]
        lines = [json.loads(l) for l in r.stdout.strip().splitlines()]
        (line,) = [d for d in lines
                   if d["metric"] == "serving_fleet_scaling"]
        assert line["responses_bitexact"] is True
        assert line["drain_under_load_ok"] is True
        assert line["drain_restart_incarnation"] >= 1
        assert line["recompiles_after_warmup"] == 0
        assert line["runlog_ok"] is True
        assert line["value"] >= 3.0
        assert line["hit_rate_ratio"] >= 0.9
        assert line["affinity_route_rate"] >= 0.5
        # Every measured request appears exactly once across the
        # fleet's merged runlogs (router-minted ids are global).
        assert line["runlog_unique_ids"] > 0
        artifact = tmp_path / "fleet_artifact.jsonl"
        artifact.write_text(r.stdout)
        slo = subprocess.run(
            [sys.executable, "tools/slo_check.py", str(artifact),
             "--metrics-key", "metrics_fleet"],
            capture_output=True, text=True, timeout=60, cwd=_REPO)
        assert slo.returncode == 0, slo.stdout + slo.stderr
        assert "SLO OK" in slo.stdout
