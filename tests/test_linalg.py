"""Decomposition tests — the coverage gap the reference left open (SURVEY.md
§4: LU/Cholesky dist paths, SVD, and inverse beyond the 3x3 permutation-matrix
case were untested there). Golden pattern: distributed op vs NumPy oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu.linalg import (
    compute_svd,
    lu_factor_array,
    symmetric_eigs,
    unpack_lu,
)
from marlin_tpu.matrix.block import BlockMatrix
from marlin_tpu.matrix.dense import DenseVecMatrix


@pytest.fixture()
def spd(rng):
    a = rng.standard_normal((24, 24))
    return a @ a.T + 24 * np.eye(24)


class TestLU:
    @pytest.mark.parametrize("mode,base", [("local", None), ("dist", 7), ("dist", 8)])
    def test_factorization(self, rng, mode, base):
        a = rng.standard_normal((20, 20))
        m = DenseVecMatrix(a)
        if base is not None:
            with mt.config_override(lu_base_size=base):
                packed, perm = lu_factor_array(m.logical, mode=mode)
        else:
            packed, perm = lu_factor_array(m.logical, mode=mode)
        l, u = unpack_lu(np.asarray(packed))
        np.testing.assert_allclose(l @ u, a[perm], rtol=1e-10, atol=1e-10)
        # perm is a permutation of 0..n-1
        assert sorted(perm.tolist()) == list(range(20))

    def test_api_contract(self, rng):
        a = rng.standard_normal((12, 12))
        lu_mat, perm = DenseVecMatrix(a).lu_decompose(mode="breeze")
        assert isinstance(lu_mat, BlockMatrix)
        l, u = unpack_lu(lu_mat.to_numpy())
        np.testing.assert_allclose(l @ u, a[perm], rtol=1e-10, atol=1e-10)

    def test_host_fetch_spanning_shard(self, rng, mesh):
        # The pivot fetch must survive a mesh-sharded perm (the multihost
        # worker found a spanning-sharded device_get crashing; in-process
        # every shard is addressable, so this pins the plain path and the
        # allgather branch is exercised by tests/test_multihost.py).
        import jax
        import jax.numpy as jnp

        from marlin_tpu.linalg.lu import _host_fetch
        from marlin_tpu.mesh import vector_sharding

        x = jax.device_put(jnp.arange(16), vector_sharding(mesh))
        np.testing.assert_array_equal(_host_fetch(x), np.arange(16))

    def test_non_square_raises(self, rng):
        with pytest.raises(ValueError):
            DenseVecMatrix(rng.standard_normal((4, 5))).lu_decompose()

    def test_bad_mode(self, rng):
        with pytest.raises(ValueError):
            DenseVecMatrix(rng.standard_normal((4, 4))).lu_decompose(mode="gpu")

    def test_singular_leading_block_falls_back(self, rng):
        # Nonsingular matrix whose leading base x base block is singular:
        # diagonal-block-local pivoting divides by a zero pivot, so the
        # non-finite tripwire must reroute to XLA's fully pivoted LU.
        n, b = 16, 4
        a = np.zeros((n, n))
        a[: n // 2, n // 2 :] = np.eye(n // 2)
        a[n // 2 :, : n // 2] = np.eye(n // 2)
        a += 0.01 * rng.standard_normal((n, n))
        # Make the leading 4x4 exactly singular (one zero column).
        a[:, 0] = 0.0
        a[n - 1, 0] = 1.0  # keep A itself nonsingular
        with mt.config_override(lu_base_size=b):
            packed, perm = lu_factor_array(DenseVecMatrix(a).logical, mode="dist")
        l, u = unpack_lu(np.asarray(packed))
        assert np.all(np.isfinite(np.asarray(packed)))
        np.testing.assert_allclose(l @ u, a[perm], rtol=1e-9, atol=1e-9)

    def test_near_singular_leading_block_falls_back(self, rng):
        # Tiny-but-nonzero leading block: values stay finite but element
        # growth explodes (~1/pivot); the growth tripwire must reroute to
        # the fully pivoted XLA path instead of returning garbage.
        n, b = 16, 4
        a = rng.standard_normal((n, n))
        a[:b, :b] *= 1e-7
        with mt.config_override(lu_base_size=b):
            packed, perm = lu_factor_array(DenseVecMatrix(a).logical, mode="dist")
        l, u = unpack_lu(np.asarray(packed))
        np.testing.assert_allclose(l @ u, a[perm], rtol=1e-8, atol=1e-8)

    def test_pivoting_needed(self):
        # Zero on the diagonal forces a row exchange.
        a = np.array([[0.0, 1.0], [1.0, 0.0]])
        packed, perm = lu_factor_array(DenseVecMatrix(a).logical, mode="local")
        l, u = unpack_lu(np.asarray(packed))
        np.testing.assert_allclose(l @ u, a[perm])


class TestCholesky:
    @pytest.mark.parametrize("mode,base", [("local", None), ("dist", 7)])
    def test_factorization(self, spd, mode, base):
        m = DenseVecMatrix(spd)
        if base is not None:
            with mt.config_override(cholesky_base_size=base):
                l = m.cholesky_decompose(mode=mode)
        else:
            l = m.cholesky_decompose(mode=mode)
        assert isinstance(l, BlockMatrix)
        ln = l.to_numpy()
        np.testing.assert_allclose(ln, np.tril(ln))  # lower triangular
        np.testing.assert_allclose(ln @ ln.T, spd, rtol=1e-10, atol=1e-8)


class TestInverse:
    def test_permutation_matrix(self):
        # The reference's 3x3 permutation-matrix inverse test (suite :340).
        p = np.array([[0.0, 1.0, 0.0], [0.0, 0.0, 1.0], [1.0, 0.0, 0.0]])
        inv = DenseVecMatrix(p).inverse()
        np.testing.assert_allclose(inv.to_numpy(), p.T, atol=1e-12)

    @pytest.mark.parametrize("mode", ["local", "dist"])
    def test_random(self, rng, mode):
        a = rng.standard_normal((18, 18)) + 18 * np.eye(18)
        with mt.config_override(lu_base_size=5):
            inv = DenseVecMatrix(a).inverse(mode=mode)
        np.testing.assert_allclose(inv.to_numpy() @ a, np.eye(18), atol=1e-8)

    def test_block_matrix_inverse(self, rng):
        a = rng.standard_normal((10, 10)) + 10 * np.eye(10)
        inv = BlockMatrix(a).inverse()
        np.testing.assert_allclose(inv.to_numpy() @ a, np.eye(10), atol=1e-8)


class TestLanczos:
    def test_top_k_eigs(self, rng):
        n, k = 60, 5
        a = rng.standard_normal((n, n))
        g = a @ a.T
        evals, evecs = symmetric_eigs(lambda x: g @ x, n, k)
        expected = np.sort(np.linalg.eigvalsh(g))[::-1][:k]
        np.testing.assert_allclose(evals, expected, rtol=1e-8)
        # Eigenvector residuals
        for i in range(k):
            r = g @ evecs[:, i] - evals[i] * evecs[:, i]
            assert np.linalg.norm(r) < 1e-6 * max(1.0, evals[i])

    def test_k_bounds(self):
        with pytest.raises(ValueError):
            symmetric_eigs(lambda x: x, 10, 10)

    def test_identity_deflation_restart(self):
        # Krylov space of the identity collapses after ONE step; without
        # deflation restarts only a single pair comes back.
        n, k = 8, 3
        evals, evecs = symmetric_eigs(lambda v: v, n, k)
        assert evals.shape == (k,) and evecs.shape == (n, k)
        np.testing.assert_allclose(evals, np.ones(k), atol=1e-10)
        np.testing.assert_allclose(evecs.T @ evecs, np.eye(k), atol=1e-8)

    def test_low_rank_deflation(self, rng):
        # Rank-2 PSD operator, k=4: two zero eigenpairs only reachable via
        # restart in the orthogonal complement.
        n, k = 12, 4
        u = np.linalg.qr(rng.standard_normal((n, 2)))[0]
        g = u @ np.diag([7.0, 3.0]) @ u.T
        evals, evecs = symmetric_eigs(lambda v: g @ v, n, k)
        np.testing.assert_allclose(evals, [7.0, 3.0, 0.0, 0.0], atol=1e-8)
        np.testing.assert_allclose(evecs.T @ evecs, np.eye(k), atol=1e-8)
        for i in range(k):
            r = g @ evecs[:, i] - evals[i] * evecs[:, i]
            assert np.linalg.norm(r) < 1e-8

    def test_repeated_top_eigenvalue_multiplicity(self):
        # Exact multiplicity > 1 AT THE TOP with a distinct eigenvalue below:
        # the exact-breakdown sweep sees each distinct value once, so without
        # the complement re-search the answer would be (10, 5) instead of
        # (10, 10).
        n, k = 3, 2
        g = np.diag([10.0, 10.0, 5.0])
        evals, evecs = symmetric_eigs(lambda v: g @ v, n, k)
        np.testing.assert_allclose(evals, [10.0, 10.0], atol=1e-8)
        np.testing.assert_allclose(evecs.T @ evecs, np.eye(k), atol=1e-8)
        for i in range(k):
            r = g @ evecs[:, i] - evals[i] * evecs[:, i]
            assert np.linalg.norm(r) < 1e-8

    def test_equal_eigenvalue_projector(self, rng):
        # Rank-2 projector u u^T + v v^T: both nonzero eigenvalues equal (1.0);
        # k=2 must return (1, 1), not (1, 0).
        n, k = 10, 2
        q = np.linalg.qr(rng.standard_normal((n, 2)))[0]
        g = q @ q.T
        evals, evecs = symmetric_eigs(lambda v: g @ v, n, k)
        np.testing.assert_allclose(evals, [1.0, 1.0], atol=1e-8)
        np.testing.assert_allclose(evecs.T @ evecs, np.eye(k), atol=1e-8)
        for i in range(k):
            r = g @ evecs[:, i] - evals[i] * evecs[:, i]
            assert np.linalg.norm(r) < 1e-8

    def test_repeated_top_with_larger_multiplicity(self):
        # Multiplicity 3 at the top plus a tail value — requires more than one
        # complement re-search sweep.
        n, k = 5, 3
        g = np.diag([10.0, 10.0, 10.0, 5.0, 1.0])
        evals, _ = symmetric_eigs(lambda v: g @ v, n, k)
        np.testing.assert_allclose(evals, [10.0, 10.0, 10.0], atol=1e-8)

    def test_clustered_eigenvalues(self, rng):
        # Near-multiplicity cluster at the top; full reorth + restarts must
        # resolve all three pairs to tolerance.
        n, k = 50, 3
        d = np.concatenate([[5.0, 5.0 - 1e-9, 5.0 - 2e-9], rng.uniform(0, 1, n - 3)])
        q = np.linalg.qr(rng.standard_normal((n, n)))[0]
        g = q @ np.diag(d) @ q.T
        evals, evecs = symmetric_eigs(lambda v: g @ v, n, k, tol=1e-12)
        np.testing.assert_allclose(evals, d[:3], rtol=1e-8)
        np.testing.assert_allclose(evecs.T @ evecs, np.eye(k), atol=1e-6)
        for i in range(k):
            r = g @ evecs[:, i] - evals[i] * evecs[:, i]
            assert np.linalg.norm(r) < 1e-6


class TestSVD:
    @pytest.fixture()
    def amat(self, rng):
        return rng.standard_normal((40, 12))

    @pytest.mark.parametrize("mode", ["local-svd", "local-eigs", "dist-eigs"])
    def test_modes_match_numpy(self, amat, mode):
        k = 4
        u, s, v = DenseVecMatrix(amat).compute_svd(k, compute_u=True, mode=mode)
        s_np = np.linalg.svd(amat, compute_uv=False)[:k]
        np.testing.assert_allclose(s, s_np, rtol=1e-8)
        # Reconstruction on the top-k subspace.
        approx = u.to_numpy() @ np.diag(s) @ v.T
        best = _best_rank_k(amat, k)
        np.testing.assert_allclose(approx, best, atol=1e-6)

    def test_no_u(self, amat):
        u, s, v = DenseVecMatrix(amat).compute_svd(3, compute_u=False, mode="local-svd")
        assert u is None and s.shape == (3,) and v.shape == (12, 3)

    def test_rcond_cutoff(self, rng):
        # Rank-2 matrix: sigma_3+ must be dropped by the rCond cutoff. Via the
        # Gramian, spurious sigmas floor at ~sqrt(eps)*sigma0 ~ 1.5e-8*sigma0
        # (true in the reference too, sigma = sqrt(eig)), so use rCond above
        # that floor.
        x = rng.standard_normal((20, 2))
        y = rng.standard_normal((2, 6))
        u, s, v = DenseVecMatrix(x @ y).compute_svd(4, mode="local-svd", r_cond=1e-6)
        assert s.shape[0] == 2

    def test_auto_mode_small(self, amat):
        u, s, v = DenseVecMatrix(amat).compute_svd(2)  # auto -> local-svd (n<100)
        np.testing.assert_allclose(
            s, np.linalg.svd(amat, compute_uv=False)[:2], rtol=1e-8
        )


def _best_rank_k(a, k):
    u, s, vt = np.linalg.svd(a, full_matrices=False)
    return u[:, :k] @ np.diag(s[:k]) @ vt[:k]


class _CountingMat:
    """Minimal ``compute_svd`` operand with per-arm call counters: a
    host Gramian behind both the local (``compute_gramian_matrix``) and
    distributed (``multiply_gramian_matrix_by``) interfaces, so a test
    can pin WHICH arm auto mode dispatched without timing anything."""

    def __init__(self, n=200, seed=0):
        rng = np.random.default_rng(seed)
        b = rng.standard_normal((2 * n, n))
        self._g = b.T @ b
        self.num_cols = n
        self.gramian_calls = 0
        self.dist_matvecs = 0

    def compute_gramian_matrix(self):
        self.gramian_calls += 1
        return self._g

    def multiply_gramian_matrix_by(self, x):
        self.dist_matvecs += 1
        return self._g @ x


class TestSVDAutoModeConstant:
    """Auto mode's local-vs-dist-eigs boundary reads
    ``MarlinConfig.svd_local_eigs_max`` (ROADMAP item 8): a measured
    policy constant (trend harness: ``run_svd_mode_crossover_sweep`` ->
    ``derive_svd_local_eigs_max``), not the reference's hard-coded
    15000. n=200 with k=4 dodges both local-svd shortcuts (n >= 100,
    k <= n/2), so the dispatch is purely the config boundary."""

    def test_default_constant_keeps_small_n_local(self):
        m = _CountingMat()
        s = compute_svd(m, 4, compute_u=False, tol=1e-8).s
        assert m.gramian_calls == 1 and m.dist_matvecs == 0
        assert s.shape == (4,)
        np.testing.assert_allclose(
            s, np.sqrt(np.linalg.eigvalsh(m._g)[::-1][:4]), rtol=1e-6)

    def test_override_routes_to_dist_eigs(self):
        from marlin_tpu.config import config_override

        m = _CountingMat()
        with config_override(svd_local_eigs_max=100):
            s = compute_svd(m, 4, compute_u=False, tol=1e-8).s
        assert m.gramian_calls == 0 and m.dist_matvecs > 0
        np.testing.assert_allclose(
            s, np.sqrt(np.linalg.eigvalsh(m._g)[::-1][:4]), rtol=1e-6)

    def test_boundary_is_inclusive(self):
        from marlin_tpu.config import config_override

        m = _CountingMat()
        with config_override(svd_local_eigs_max=m.num_cols):
            compute_svd(m, 4, compute_u=False, tol=1e-8)
        assert m.gramian_calls == 1 and m.dist_matvecs == 0


class TestDeviceSweep:
    """Device-resident Lanczos (matvec_jax chunked recurrence) vs host sweep."""

    def test_matches_host_sweep(self, rng):
        n, k = 60, 5
        g = rng.standard_normal((n, n))
        g = g @ g.T
        gj = jnp.asarray(g)
        host = symmetric_eigs(lambda v: g @ v, n, k)
        dev = symmetric_eigs(
            lambda v: g @ v, n, k, matvec_jax=lambda v: gj @ v
        )
        np.testing.assert_allclose(dev[0], host[0], rtol=1e-9)
        # Eigenvectors up to sign.
        for i in range(k):
            d = min(
                np.linalg.norm(dev[1][:, i] - host[1][:, i]),
                np.linalg.norm(dev[1][:, i] + host[1][:, i]),
            )
            assert d < 1e-6

    def test_exact_breakdown_identity(self):
        # Identity: invariant subspace on step 1 -> deflation restarts, all
        # eigenvalues 1 (the ARPACK-deflation case class through the device
        # sweep's scale-aware breakdown detector).
        n, k = 16, 3
        evals, evecs = symmetric_eigs(
            lambda v: v, n, k, matvec_jax=lambda v: v
        )
        np.testing.assert_allclose(evals, np.ones(k), rtol=1e-10)
        np.testing.assert_allclose(evecs.T @ evecs, np.eye(k), atol=1e-8)

    def test_repeated_top_eigenvalue(self):
        # diag(10, 10, 5, ...): repeated top must come back with multiplicity
        # (the ADVICE deflation case) through the device sweep too.
        d = np.array([10.0, 10.0, 5.0, 2.0, 1.0, 0.5, 0.25, 0.1])
        g = np.diag(d)
        gj = jnp.asarray(g)
        evals, _ = symmetric_eigs(
            lambda v: g @ v, len(d), 2, matvec_jax=lambda v: gj @ v
        )
        np.testing.assert_allclose(evals, [10.0, 10.0], rtol=1e-8)


class TestShardedDecompositions:
    """VERDICT next-3: the Schur GEMM must RUN sharded — feed the single-jit
    panel sweeps block-sharded inputs and require the factor to come back
    sharded over every device (GSPMD propagates (mr, mc) through the whole
    fori_loop) with the oracle still satisfied."""

    def test_lu_on_sharded_input_stays_sharded(self, rng, mesh):
        from marlin_tpu.mesh import block_sharding

        n = 192
        a = rng.standard_normal((n, n))
        a_sh = jax.device_put(jnp.asarray(a), block_sharding(mesh))
        with mt.config_override(lu_base_size=48):
            packed, perm = lu_factor_array(a_sh, mode="dist")
        assert len(packed.sharding.device_set) == len(mesh.devices.flat)
        l, u = unpack_lu(np.asarray(packed))
        np.testing.assert_allclose(a[perm], l @ u, atol=1e-10)

    def test_cholesky_on_sharded_input_stays_sharded(self, rng, mesh):
        from marlin_tpu.mesh import block_sharding
        from marlin_tpu.linalg.cholesky import cholesky_factor_array

        n = 192
        g = rng.standard_normal((n, n))
        a = g @ g.T + n * np.eye(n)
        a_sh = jax.device_put(jnp.asarray(a), block_sharding(mesh))
        with mt.config_override(cholesky_base_size=48):
            l = cholesky_factor_array(a_sh, mode="dist")
        assert len(l.sharding.device_set) == len(mesh.devices.flat)
        ln = np.asarray(l)
        np.testing.assert_allclose(ln @ ln.T, a, rtol=1e-10, atol=1e-8)


class TestSolve:
    def test_lu_solve_matrix_rhs(self, rng):
        from marlin_tpu.linalg import solve

        n = 96
        a = rng.standard_normal((n, n)) + n * np.eye(n)
        b = rng.standard_normal((n, 5))
        with mt.config_override(lu_base_size=32):
            x = np.asarray(solve(jnp.asarray(a), jnp.asarray(b), mode="dist"))
        np.testing.assert_allclose(a @ x, b, rtol=1e-8, atol=1e-8)

    def test_vector_rhs_and_local_mode(self, rng):
        from marlin_tpu.linalg import solve

        a = rng.standard_normal((12, 12)) + 12 * np.eye(12)
        b = rng.standard_normal(12)
        x = np.asarray(solve(jnp.asarray(a), jnp.asarray(b)))
        assert x.shape == (12,)
        np.testing.assert_allclose(a @ x, b, rtol=1e-9)

    def test_spd_route(self, rng):
        from marlin_tpu.linalg import solve

        n = 64
        g = rng.standard_normal((n, n))
        a = g @ g.T + n * np.eye(n)
        b = rng.standard_normal((n, 3))
        with mt.config_override(cholesky_base_size=32):
            x = np.asarray(solve(jnp.asarray(a), jnp.asarray(b),
                                 mode="dist", assume_spd=True))
        np.testing.assert_allclose(a @ x, b, rtol=1e-8, atol=1e-8)

    def test_shape_errors(self, rng):
        from marlin_tpu.linalg import solve

        with pytest.raises(ValueError):
            solve(jnp.zeros((3, 4)), jnp.zeros(3))
        with pytest.raises(ValueError):
            solve(jnp.eye(3), jnp.zeros(4))


class TestLinalgPrecision:
    """The decompositions must stay full-precision even when the global
    matmul_precision is relaxed (on TPU, "default" runs f32 matmuls through
    bfloat16 passes — measured LU reconstruction error 0.69 at n=2048 under
    round-2 bench's global "default"). CPU ignores precision numerically, so
    the contract is pinned on PRODUCTION behavior: every public entry point
    must enter the linalg_precision ambient scope (spied via
    jax.default_matmul_precision) around its device work."""

    @pytest.fixture()
    def spy(self, monkeypatch):
        seen = []
        real = jax.default_matmul_precision

        def record(p):
            seen.append(p)
            return real(p)

        monkeypatch.setattr(jax, "default_matmul_precision", record)
        return seen

    def _drive(self, fn, spy, expect):
        """expect = exact number of scope entries: composite entry points
        (dist inverse/solve) must enter for their OWN solves in addition to
        the nested factorization's entry — a count assertion catches a
        deleted wrapper that a mere membership check would miss."""
        spy.clear()
        out = fn()
        assert spy.count("highest") == expect, (
            f"expected {expect} linalg scope entries, saw {spy}"
        )
        return out

    def test_every_entry_point_enters_scope(self, rng, spy):
        from marlin_tpu.linalg.cholesky import cholesky_factor_array
        from marlin_tpu.linalg.inverse import inverse
        from marlin_tpu.linalg.solve import solve

        a32 = jnp.asarray(rng.standard_normal((16, 16)), jnp.float32)
        spd = jnp.asarray(
            np.asarray(a32) @ np.asarray(a32).T + 16 * np.eye(16, dtype=np.float32)
        )
        b = jnp.asarray(rng.standard_normal(16), jnp.float32)
        with mt.config_override(
            matmul_precision="default", lu_base_size=8, cholesky_base_size=8
        ):
            self._drive(lambda: lu_factor_array(a32, mode="dist"), spy, 1)
            self._drive(lambda: lu_factor_array(a32, mode="local"), spy, 1)
            self._drive(lambda: cholesky_factor_array(spd, mode="dist"), spy, 1)
            self._drive(lambda: cholesky_factor_array(spd, mode="local"), spy, 1)
            self._drive(
                lambda: inverse(a32 + 16 * jnp.eye(16), mode="dist"), spy, 2)
            self._drive(
                lambda: inverse(a32 + 16 * jnp.eye(16), mode="local"), spy, 1)
            self._drive(
                lambda: solve(a32 + 16 * jnp.eye(16), b, mode="dist"), spy, 2)
            self._drive(
                lambda: solve(a32 + 16 * jnp.eye(16), b, mode="local"), spy, 1)
            self._drive(
                lambda: solve(spd, b, mode="dist", assume_spd=True), spy, 2
            )

    def test_scope_respects_linalg_precision_config(self, rng, spy):
        a32 = jnp.asarray(rng.standard_normal((16, 16)), jnp.float32)
        with mt.config_override(linalg_precision="high", lu_base_size=8):
            lu_factor_array(a32, mode="dist")
        assert "high" in spy and "highest" not in spy

    def test_dist_results_match_local_under_relaxed_global(self, rng):
        # End-to-end: dist LU under a relaxed global equals the local path.
        a = rng.standard_normal((20, 20))
        with mt.config_override(matmul_precision="default", lu_base_size=5):
            packed, perm = lu_factor_array(jnp.asarray(a), mode="dist")
        l, u = unpack_lu(np.asarray(packed))
        np.testing.assert_allclose(l @ u, a[perm], rtol=1e-10, atol=1e-10)


class TestLanczosOperandProtocol:
    """The Gramian operator must thread its data through the device chunk as
    a runtime ARGUMENT (op.apply/op.operand), not a closure capture: captured
    device arrays become XLA constants of the chunk program, and constant
    handling at Gramian scale stalled compilation >25 min at 200k x 2048 on
    v5e (fixed: 17 s end-to-end)."""

    def test_operator_exposes_protocol(self, rng):
        m = DenseVecMatrix(rng.standard_normal((64, 16)))
        op = m.gramian_matvec_operator()
        assert callable(getattr(op, "apply", None))
        assert op.operand is m._data

    def test_chunk_jaxpr_has_no_operand_sized_consts(self, rng):
        from marlin_tpu.linalg.lanczos import _device_chunk_fn

        m = DenseVecMatrix(rng.standard_normal((64, 16)).astype(np.float32))
        op = m.gramian_matvec_operator()
        n = 16
        f = _device_chunk_fn(op, 12, 0, n, jnp.float32)
        carry = (
            jnp.zeros((13, n), jnp.float32).at[0, 0].set(1.0),
            jnp.zeros((12,), jnp.float32),
            jnp.zeros((12,), jnp.float32),
            jnp.zeros((n, 0), jnp.float32),
            jnp.zeros((), jnp.int32),
            jnp.zeros((), jnp.bool_),
        )
        jx = jax.make_jaxpr(f)(op.operand, carry)
        data_elems = int(np.prod(m._data.shape))
        big = [c for c in jx.consts if getattr(c, "size", 0) >= data_elems]
        assert not big, f"operand captured as const: {[c.shape for c in big]}"
        # and the chunk still computes a correct Lanczos step
        out = f(op.operand, carry)
        assert int(out[4]) == 12

    def test_half_implemented_protocol_rejected(self):
        from marlin_tpu.linalg.lanczos import _operator_protocol

        def op(v):
            return v

        assert _operator_protocol(op) == (None, ())
        op.apply = lambda a, v: v
        with pytest.raises(TypeError, match="BOTH"):
            _operator_protocol(op)
        op.operand = jnp.zeros((2, 2))
        assert _operator_protocol(op)[0] is op.apply
        del op.apply
        with pytest.raises(TypeError, match="BOTH"):
            _operator_protocol(op)


class TestLUPanelPivoting:
    """The blocked LU's pivot search must span every row below the diagonal
    (LAPACK getrf), not just the diagonal block: block-local pivoting showed
    element growth 1.3e5 on a random 16k f32 matrix on v5e (gate ~1.3e4) and
    its XLA-full-lu fallback is broken at 16k (scoped-vmem bug). These cases
    all break block-local pivoting."""

    def _check(self, a, base, tol=1e-10):
        with mt.config_override(lu_base_size=base):
            packed, perm = lu_factor_array(jnp.asarray(a), mode="dist")
        l, u = unpack_lu(np.asarray(packed))
        scale = max(np.max(np.abs(a)), 1e-30)
        assert np.max(np.abs(a[perm] - l @ u)) / scale < tol
        # True partial pivoting bounds every multiplier: |L| <= 1.
        assert np.max(np.abs(np.tril(np.asarray(packed), -1))) <= 1.0 + 1e-12
        assert sorted(perm.tolist()) == list(range(a.shape[0]))
        return packed, perm

    def test_zero_leading_block(self, rng):
        a = rng.standard_normal((32, 32))
        a[:8, :8] = 0.0  # block-local pivoting divides by ~0 here
        self._check(a, 8)

    def test_tiny_leading_block_growth_bounded(self, rng):
        a = rng.standard_normal((32, 32))
        a[:8, :8] *= 1e-12  # growth bomb for block-local pivoting
        packed, _ = self._check(a, 8)
        growth = np.max(np.abs(packed)) / np.max(np.abs(a))
        assert growth < 100.0  # partial pivoting keeps growth small

    def test_rank_deficient_column_dgetf2_semantics(self, rng):
        # A dependent column yields U[c,c]=0 with zero L column — no NaNs.
        a = rng.standard_normal((24, 24))
        a[:, 5] = a[:, 3] * 2.0 - a[:, 1]
        packed, _ = self._check(a, 6, tol=1e-9)
        assert np.isfinite(np.asarray(packed)).all()

    def test_all_zero_matrix(self):
        with mt.config_override(lu_base_size=4):
            packed, perm = lu_factor_array(jnp.zeros((16, 16)), mode="dist")
        assert float(jnp.max(jnp.abs(packed))) == 0.0
        assert sorted(perm.tolist()) == list(range(16))

    def test_pivot_choices_match_lapack(self, rng):
        import scipy.linalg as sla

        a = rng.standard_normal((24, 24))
        with mt.config_override(lu_base_size=6):
            packed, perm = lu_factor_array(jnp.asarray(a), mode="dist")
        lu_s, piv = sla.lu_factor(a)
        perm_s = np.arange(24)
        for i, p in enumerate(piv):
            perm_s[[i, p]] = perm_s[[p, i]]
        assert np.array_equal(perm, perm_s)
        np.testing.assert_allclose(np.asarray(packed), lu_s, atol=1e-9)


class TestQR:
    """CholeskyQR2 thin QR + seminormal-equations least squares (beyond the
    reference's L4 set; the tall row-sharded regime its DenseVecMatrix
    lives in). Oracle: numpy QR up to column-sign, machine-precision
    orthogonality, and lstsq vs numpy."""

    def _check_qr(self, a, mode):
        from marlin_tpu.linalg import qr_factor_array

        q, r = qr_factor_array(jnp.asarray(a), mode=mode)
        q, r = np.asarray(q, np.float64), np.asarray(r, np.float64)
        m, n = a.shape
        assert q.shape == (m, n) and r.shape == (n, n)
        np.testing.assert_allclose(q @ r, a, rtol=1e-8, atol=1e-8)
        np.testing.assert_allclose(q.T @ q, np.eye(n), atol=1e-9)
        assert np.allclose(np.tril(r, -1), 0)  # R upper triangular
        return q, r

    def test_tall_tsqr_matches_numpy_up_to_sign(self, rng):
        a = rng.standard_normal((7000, 24))  # auto -> dist -> CholeskyQR2
        q, r = self._check_qr(a, "auto")
        qn, rn = np.linalg.qr(a)
        sign = np.sign(np.diag(rn)) * np.sign(np.diag(r))
        np.testing.assert_allclose(r * sign[:, None], rn, rtol=1e-6,
                                   atol=1e-8)

    def test_tsqr_moderately_ill_conditioned(self, rng):
        # cond ~ 1e4: one-pass CholeskyQR loses orthogonality as cond^2*eps
        # (~1e-8 at f64 would pass, but f32-graded scales matter); the
        # second pass must restore machine-precision orthogonality.
        u = np.linalg.qr(rng.standard_normal((600, 12)))[0]
        a = u * np.logspace(0, 4, 12)[None, :]
        self._check_qr(a, "tsqr")

    def test_square_routes_local(self, rng):
        a = rng.standard_normal((32, 32))
        self._check_qr(a, "auto")

    def test_tsqr_rejects_fat(self, rng):
        from marlin_tpu.linalg import qr_factor_array

        with pytest.raises(ValueError, match="m >= n"):
            qr_factor_array(jnp.asarray(rng.standard_normal((4, 8))),
                            mode="tsqr")

    def test_qr_decompose_type_roundtrip(self, rng):
        from marlin_tpu.linalg.qr import qr_decompose

        m = DenseVecMatrix(rng.standard_normal((40, 8)))
        qm, r = qr_decompose(m, mode="tsqr")
        assert isinstance(qm, DenseVecMatrix)
        np.testing.assert_allclose(
            qm.to_numpy() @ np.asarray(r), m.to_numpy(), rtol=1e-8,
            atol=1e-8)

    def test_lstsq_matches_numpy(self, rng):
        from marlin_tpu.linalg import lstsq

        a = rng.standard_normal((7000, 16))
        x_true = rng.standard_normal((16, 3))
        b = a @ x_true + 0.01 * rng.standard_normal((7000, 3))
        x = np.asarray(lstsq(jnp.asarray(a), jnp.asarray(b)))
        x_np = np.linalg.lstsq(a, b, rcond=None)[0]
        np.testing.assert_allclose(x, x_np, rtol=1e-6, atol=1e-8)

    def test_lstsq_vector_rhs_and_local_route(self, rng):
        from marlin_tpu.linalg import lstsq

        a = rng.standard_normal((40, 8))  # small -> local route
        b = rng.standard_normal(40)
        x = np.asarray(lstsq(jnp.asarray(a), jnp.asarray(b)))
        assert x.shape == (8,)
        np.testing.assert_allclose(
            x, np.linalg.lstsq(a, b, rcond=None)[0], rtol=1e-6, atol=1e-8)

    def test_lstsq_mode_validation_and_fat_guard(self, rng):
        from marlin_tpu.linalg import lstsq

        a = jnp.asarray(rng.standard_normal((4, 8)))
        b = jnp.asarray(rng.standard_normal(4))
        with pytest.raises(ValueError, match="m >= n"):
            lstsq(a, b, mode="tsqr")
        with pytest.raises(ValueError, match="Do not support mode"):
            lstsq(a, b, mode="dist")

    def test_f32_extreme_condition_falls_back_finite(self, rng):
        # f32 CholeskyQR limit is cond ~ 1/sqrt(eps_f32) ~ 3e3; beyond it
        # the Gramian Cholesky goes NaN and the runtime fallback must
        # produce a finite, orthogonal factorization via XLA QR.
        from marlin_tpu.linalg import lstsq, qr_factor_array

        u = np.linalg.qr(rng.standard_normal((7000, 8)))[0]
        a = jnp.asarray(u * np.logspace(0, 7, 8)[None, :], jnp.float32)
        q, r = qr_factor_array(a, mode="tsqr")
        qn = np.asarray(q, np.float64)
        assert np.isfinite(qn).all()
        np.testing.assert_allclose(qn.T @ qn, np.eye(8), atol=1e-4)
        x = lstsq(a, jnp.asarray(rng.standard_normal(7000), jnp.float32))
        assert np.isfinite(np.asarray(x)).all()
