"""Paged KV cache tests (serving/pages.py, serving/prefix.
PagedPrefixIndex, slots.prefill_chunk_into_row_paged,
transformer._chunk_states_paged, engine paged mode).

The acceptance claims, each pinned mechanically:

* BIT-EXACTNESS — the paged engine (gather-read / scatter-write through
  page tables) emits tokens bit-identical to B=1 ``generate`` for
  plain / rope+GQA / int8-cache / eos configs, with prefix sharing on
  AND off: the page-gathered read hands attention identical bytes, and
  masked positions carry exactly-zero weight in both representations
  (docs/serving.md §paged KV).
* ZERO COPY — prefix hits admit by page-table aliasing:
  ``admission_copy_bytes == 0``, the zero-copy hit counter moves, and
  aliased pages are bytewise IMMUTABLE while other rows decode over
  them.
* REFCOUNT DISCIPLINE — a randomized property drive (store / hit /
  evict / release interleavings) against a host-side shadow model: no
  page freed while referenced, every freed page returns to the free
  list exactly once, the allocator never hands out a live page.
* NO REBUILD — pool buffer pointers stay stable across admissions and
  rounds (donation), and compiles are bounded: 1 paged round + 2 paged
  chunk compiles for a whole shared-prefix workload.
* CAPACITY — at equal pool bytes the paged engine holds strictly more
  concurrent sequences than the row-granular cache (the
  reservation-exact + shared-prefix win the bench line quantifies).
"""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from marlin_tpu.models import TransformerConfig, generate, init_params
from marlin_tpu.obs.metrics import MetricsRegistry
from marlin_tpu.serving import PAGE, PagePool, ServingEngine
from marlin_tpu.serving.engine import _decode_round_paged
from marlin_tpu.serving.pages import SINK_PAGE, HostKVTier
from marlin_tpu.serving.prefix import PagedPrefixIndex
from marlin_tpu.serving.slots import prefill_chunk_into_row_paged


def _cfg(**kw):
    base = dict(vocab=48, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                max_len=160)
    base.update(kw)
    return TransformerConfig(**base)


VARIANTS = [{}, {"rope": True, "n_kv_heads": 1}, {"kv_quant": "int8"}]


def _shared_prefix_workload(cfg, rng, prefix_len=48, n=6):
    shared = rng.integers(0, cfg.vocab, prefix_len).astype(np.int32)
    out = []
    for i in range(n - 1):
        tail = rng.integers(0, cfg.vocab, 4 + i).astype(np.int32)
        out.append((np.concatenate([shared, tail]), 4 + i))
    out.append((rng.integers(0, cfg.vocab, 9).astype(np.int32), 5))
    return out


def _run_workload(engine, workload, waves=1):
    ids = {}
    finished = []
    per = -(-len(workload) // waves)
    for w in range(waves):
        for prompt, steps in workload[w * per:(w + 1) * per]:
            ids[engine.submit(prompt, steps)] = (prompt, steps)
        if w + 1 < waves:
            finished += engine.step()
    finished += engine.run()
    return ids, {r.request_id: r for r in finished}


class TestPagePoolConfig:
    """The small-fix satellite: typed construction validation."""

    def test_n_pages_must_be_positive_int(self):
        cfg = _cfg()
        for bad in (0, -1, 1.5, "8", True):
            with pytest.raises(ValueError, match="n_pages"):
                PagePool(cfg, bad)

    def test_max_len_must_tile_pages(self):
        with pytest.raises(ValueError, match="divisible"):
            PagePool(_cfg(max_len=150), 4)

    def test_window_rejected(self):
        with pytest.raises(ValueError, match="window"):
            PagePool(_cfg(window=32), 4)

    def test_engine_rejects_prefix_cache_with_kv_pages(self):
        from marlin_tpu.serving import PrefixCache

        cfg = _cfg()
        params = init_params(cfg, seed=0)
        with pytest.raises(ValueError, match="mutually exclusive"):
            ServingEngine(params, cfg, batch=2, kv_pages=8,
                          prefix_cache=PrefixCache(cfg, pool_rows=2))

    def test_prefix_sharing_flag_is_paged_only(self):
        # prefix_sharing=False on a contiguous engine would silently do
        # nothing the user asked for — typed error, like the
        # kv_pages+prefix_cache conflict.
        cfg = _cfg()
        params = init_params(cfg, seed=0)
        with pytest.raises(ValueError, match="prefix_sharing"):
            ServingEngine(params, cfg, batch=2, prefix_sharing=False)

    def test_submit_rejects_request_bigger_than_pool(self):
        cfg = _cfg()
        params = init_params(cfg, seed=0)
        eng = ServingEngine(params, cfg, batch=2, kv_pages=3)
        with pytest.raises(ValueError, match="KV pages"):
            eng.submit(np.zeros(40, np.int32), 40)  # 5 pages > 3


class TestPagePoolHost:
    def test_alloc_ref_unref_free_discipline(self):
        pool = PagePool(_cfg(), 4)
        a = pool.alloc(3)
        assert sorted(a) == [1, 2, 3] and pool.n_free == 1
        assert pool.alloc(2) is None          # short: no partial grant
        assert pool.alloc_failures == 1
        pool.ref([a[0]])                      # alias: refcount 2
        assert pool.refcount(a[0]) == 2
        pool.unref(a)                         # row release
        assert pool.n_free == 3               # a[0] still index-held
        assert pool.refcount(a[0]) == 1
        pool.unref([a[0]])
        assert pool.n_free == 4 and pool.refcount(a[0]) == 0
        with pytest.raises(RuntimeError, match="double free"):
            pool.unref([a[0]])
        with pytest.raises(RuntimeError, match="free/unallocated"):
            pool.ref([a[0]])
        assert pool.alloc(0) == []            # fully-aliased admission

    def test_sink_page_never_allocated(self):
        pool = PagePool(_cfg(), 4)
        got = pool.alloc(4)
        assert SINK_PAGE not in got
        assert pool.alloc(1) is None


class TestRefcountProperty:
    def test_randomized_interleavings_match_shadow_model(self):
        """Seeded property drive: interleaved admission (alloc + alias
        ref), store (ref), release (unref), and eviction (unref)
        against a shadow refcount model. Invariants after every op:
        pool refcounts == shadow, free list == zero-ref pages with no
        duplicates, allocator never hands out a live page, and total
        frees == pages whose last reference dropped."""
        cfg = _cfg(d_model=8, n_heads=2, n_layers=1, d_ff=16, max_len=64)
        pool = PagePool(cfg, 12)
        index = PagedPrefixIndex(pool)
        rng = random.Random(1234)
        shadow = {}           # page -> refcount
        rows = {}             # row id -> held page list
        entries = {}          # entry key -> page tuple (mirror of index)
        vocab = 997
        prompts = {}          # entry key -> tokens
        next_row = 0
        freed_total = 0

        def check():
            live = {p: n for p, n in shadow.items() if n > 0}
            assert dict(pool._refs) == live
            free = sorted(pool._free)
            assert free == sorted(set(free)), "duplicate free-list entry"
            assert set(free) == set(range(1, 13)) - set(live), \
                "free list != zero-ref pages"
            assert SINK_PAGE not in live and SINK_PAGE not in free
            assert pool.frees == freed_total

        for step in range(400):
            op = rng.choice(["admit", "admit", "store", "release",
                             "release", "evict"])
            if op == "admit":
                n = rng.randint(1, 4)
                use_alias = entries and rng.random() < 0.5
                alias = []
                if use_alias:
                    key = rng.choice(sorted(entries))
                    alias = list(entries[key])[:rng.randint(
                        1, len(entries[key]))]
                    pool.ref(alias)
                    for p in alias:
                        shadow[p] = shadow.get(p, 0) + 1
                fresh = pool.alloc(n)
                if fresh is None:
                    if alias:
                        pool.unref(alias)
                        for p in alias:
                            shadow[p] -= 1
                            if shadow[p] == 0:
                                freed_total += 1
                else:
                    for p in fresh:
                        assert shadow.get(p, 0) == 0, \
                            "allocator handed out a live page"
                        shadow[p] = 1
                    rows[next_row] = alias + fresh
                    next_row += 1
            elif op == "store" and rows:
                row = rng.choice(sorted(rows))
                pages = rows[row][:rng.randint(1, len(rows[row]))]
                toks = np.asarray(
                    [rng.randrange(vocab) for _ in
                     range(len(pages) * PAGE)], np.int32)
                stored = index.store(toks, pages)
                if stored:
                    key = toks.tobytes()
                    entries[key] = tuple(pages[:stored // PAGE])
                    prompts[key] = toks
                    for p in entries[key]:
                        shadow[p] += 1
            elif op == "release" and rows:
                row = rng.choice(sorted(rows))
                held = rows.pop(row)
                pool.unref(held)
                for p in held:
                    shadow[p] -= 1
                    if shadow[p] == 0:
                        freed_total += 1
            elif op == "evict" and entries:
                # Evict the index's LRU; mirror by removing SOME entry —
                # resolve which one vanished by re-querying the index.
                before = set(e.tokens.tobytes()
                             for e in index._entries.values())
                assert index.evict_lru()
                after = set(e.tokens.tobytes()
                            for e in index._entries.values())
                (gone,) = before - after
                for p in entries.pop(gone):
                    shadow[p] -= 1
                    if shadow[p] == 0:
                        freed_total += 1
                prompts.pop(gone)
            check()

    def test_spill_restore_interleavings_match_shadow_model(self):
        """The PR 9 drive extended with the host tier's transitions
        (ISSUE 16): eviction now SPILLS a sole-holder entry (pages
        freed, payload parked host-side) and a restore re-pins freshly
        allocated pages exactly once. Further extended with the
        scheduler's LIVE-ROW transitions (ISSUE 17): a freeze spills a
        row's whole page complement as a PINNED host entry (pages
        freed, payload + tokens parked under the freeze key), a thaw
        re-reserves the complement, fetches the pinned payload and
        drops it — exactly once each way. Shadow invariants after
        every op: refcounts match, the allocator never hands out a
        live page, a spill only ever fires when the index's pin was
        the LAST reference, the tier's payload set mirrors the index's
        spilled entries one-for-one, and the tier's pinned-row set
        (and its byte ledger) mirrors the shadow's frozen rows."""
        cfg = _cfg(d_model=8, n_heads=2, n_layers=1, d_ff=16, max_len=64)
        reg = MetricsRegistry()
        pool = PagePool(cfg, 12, registry=reg)
        tier = HostKVTier(pool, registry=reg)
        index = PagedPrefixIndex(pool, registry=reg, host_tier=tier)
        rng = random.Random(4321)
        shadow = {}           # page -> refcount
        rows = {}             # row id -> held page list
        resident = {}         # tokens-bytes -> page tuple
        spilled = set()       # tokens-bytes of spilled entries
        frozen = {}           # freeze key -> (n_pages, nbytes)
        next_row = 0
        n_freezes = 0
        freed_total = 0

        def eid_of(key):
            (eid,) = [e for e, ent in index._entries.items()
                      if ent.tokens.tobytes() == key]
            return eid

        def check():
            live = {p: n for p, n in shadow.items() if n > 0}
            assert dict(pool._refs) == live
            free = sorted(pool._free)
            assert free == sorted(set(free))
            assert set(free) == set(range(1, 13)) - set(live)
            assert SINK_PAGE not in live and SINK_PAGE not in free
            assert pool.frees == freed_total
            # Every spilled entry's payload is really in the tier (the
            # tier may hold MORE: a restore leaves the payload cached
            # host-side — content-keyed, it stays valid — and only the
            # host budget's LRU or a spilled-entry removal prunes it).
            sp_keys = {ent.host_key
                       for ent in index._entries.values()
                       if ent.state == "spilled"}
            assert sp_keys <= set(tier._entries.keys())
            s = index.summary()
            assert s["prefix_spilled_entries"] == len(spilled)
            # Pinned frozen rows: the tier's row set and byte ledger
            # mirror the shadow exactly — a freeze that leaked its
            # entry (or a thaw that forgot drop_row) shows up here.
            ts = tier.summary()
            assert set(tier._rows) == set(frozen)
            assert ts["host_rows"] == len(frozen)
            assert ts["host_row_bytes"] == sum(
                nb for _, nb in frozen.values())

        for step in range(500):
            op = rng.choice(["admit", "admit", "store", "release",
                             "release", "evict", "restore",
                             "freeze", "thaw"])
            if op == "admit":
                n = rng.randint(1, 4)
                use_alias = resident and rng.random() < 0.5
                alias = []
                if use_alias:
                    key = rng.choice(sorted(resident))
                    alias = list(resident[key])[:rng.randint(
                        1, len(resident[key]))]
                    pool.ref(alias)
                    for p in alias:
                        shadow[p] = shadow.get(p, 0) + 1
                fresh = pool.alloc(n)
                if fresh is None:
                    if alias:
                        pool.unref(alias)
                        for p in alias:
                            shadow[p] -= 1
                            if shadow[p] == 0:
                                freed_total += 1
                else:
                    for p in fresh:
                        assert shadow.get(p, 0) == 0, \
                            "allocator handed out a live page"
                        shadow[p] = 1
                    rows[next_row] = alias + fresh
                    next_row += 1
            elif op == "store" and rows:
                row = rng.choice(sorted(rows))
                pages = rows[row][:rng.randint(1, len(rows[row]))]
                toks = np.asarray(
                    [rng.randrange(997) for _ in
                     range(len(pages) * PAGE)], np.int32)
                stored = index.store(toks, pages)
                if stored:
                    key = toks.tobytes()
                    resident[key] = tuple(pages[:stored // PAGE])
                    for p in resident[key]:
                        shadow[p] += 1
            elif op == "release" and rows:
                row = rng.choice(sorted(rows))
                held = rows.pop(row)
                pool.unref(held)
                for p in held:
                    shadow[p] -= 1
                    if shadow[p] == 0:
                        freed_total += 1
            elif op == "evict" and index._entries:
                before = {ent.tokens.tobytes(): (ent.state, ent.pages)
                          for ent in index._entries.values()}
                assert index.evict_lru()
                after = {ent.tokens.tobytes(): ent.state
                         for ent in index._entries.values()}
                gone = set(before) - set(after)
                if gone:
                    # Removed outright: an aliased resident entry (no
                    # spill while a row still references the pages) or
                    # a spilled one (payload dropped with it).
                    (k,) = gone
                    state, pages = before[k]
                    if state == "resident":
                        assert any(shadow[p] > 1 for p in pages), \
                            "sole-holder entry removed instead of spilled"
                        for p in pages:
                            shadow[p] -= 1
                            if shadow[p] == 0:
                                freed_total += 1
                        resident.pop(k)
                    else:
                        spilled.discard(k)
                else:
                    # Spilled: only legal when the index held the LAST
                    # reference on every page.
                    (k,) = [k for k, st in after.items()
                            if st == "spilled" and before[k][0]
                            == "resident"]
                    _, pages = before[k]
                    for p in pages:
                        assert shadow[p] == 1, \
                            "spill fired with a live alias"
                        shadow[p] = 0
                        freed_total += 1
                    resident.pop(k)
                    spilled.add(k)
            elif op == "restore" and spilled:
                key = rng.choice(sorted(spilled))
                eid = eid_of(key)
                n = index._entries[eid].length // PAGE
                fresh = pool.alloc(n)
                if fresh is None:
                    check()
                    continue  # pool full: the engine would evict first
                for p in fresh:
                    assert shadow.get(p, 0) == 0, \
                        "allocator handed out a live page"
                    shadow[p] = 1  # the restoring row's reservation
                index.rebind(eid, fresh)
                for p in fresh:
                    shadow[p] += 1  # the rebind re-pins exactly once
                rows[next_row] = list(fresh)
                next_row += 1
                spilled.discard(key)
                resident[key] = tuple(fresh)
            elif op == "freeze" and rows:
                # A live row's whole complement spills as a PINNED
                # entry; the row's references drop (the engine frees
                # the pages after the gather). Aliased pages survive
                # in their other holders — the gather copied the KV.
                row = rng.choice(sorted(rows))
                held = rows.pop(row)
                key = f"frz-{n_freezes}"
                n_freezes += 1
                toks = np.asarray(
                    [rng.randrange(997) for _ in
                     range(len(held) * PAGE)], np.int32)
                res = tier.spill_row(key, toks, held)
                assert res is not None  # no budget: never refused
                nbytes, _ = res
                pool.unref(held)
                for p in held:
                    shadow[p] -= 1
                    if shadow[p] == 0:
                        freed_total += 1
                frozen[key] = (len(held), nbytes)
            elif op == "thaw" and frozen:
                key = rng.choice(sorted(frozen))
                n, nbytes = frozen[key]
                fresh = pool.alloc(n)
                if fresh is None:
                    check()
                    continue  # pool full: the engine keeps it frozen
                for p in fresh:
                    assert shadow.get(p, 0) == 0, \
                        "allocator handed out a live page"
                    shadow[p] = 1  # the thawed row's reservation
                got = tier.fetch_row(key)
                assert got is not None, "pinned row vanished"
                _, got_toks, got_bytes = got
                assert got_bytes == nbytes
                assert len(got_toks) == n * PAGE
                tier.drop_row(key)
                assert tier.fetch_row(key) is None  # dropped once
                rows[next_row] = list(fresh)
                next_row += 1
                frozen.pop(key)
            check()


class TestPagedEngineExactness:
    # Tier-1 wall-clock budget (ROADMAP 9): default variant in tier-1,
    # rope/GQA + int8 variants (~14 s of compile each) under -m slow.
    @pytest.mark.parametrize("kw", [VARIANTS[0]] + [
        pytest.param(v, marks=pytest.mark.slow) for v in VARIANTS[1:]])
    def test_paged_outputs_bit_exact_vs_b1_generate(self, kw):
        # THE acceptance pin: the paged engine (sharing on) against the
        # B=1 generate oracle — which transitively pins it against the
        # contiguous chunked engine (test_prefix_cache pins that one
        # against the same oracle).
        cfg = _cfg(**kw)
        params = init_params(cfg, seed=0)
        rng = np.random.default_rng(9)
        workload = _shared_prefix_workload(cfg, rng)
        eng = ServingEngine(params, cfg, batch=3, round_steps=4,
                            kv_pages=40)
        ids, done = _run_workload(eng, workload, waves=3)
        assert eng.stats.n_completed == len(workload)
        assert eng.stats.n_prefix_hits > 0  # the hits really happened
        assert eng.stats.admission_copy_bytes == 0
        for rid, (prompt, steps) in ids.items():
            ref = np.asarray(generate(
                params, jnp.asarray(prompt[None], jnp.int32), steps,
                cfg))[0]
            np.testing.assert_array_equal(done[rid].tokens, ref,
                                          err_msg=f"request {rid}")

    def test_sharing_on_bitwise_equals_sharing_off(self):
        cfg = _cfg()
        params = init_params(cfg, seed=0)
        rng = np.random.default_rng(9)
        workload = _shared_prefix_workload(cfg, rng)

        def run(sharing):
            eng = ServingEngine(params, cfg, batch=3, round_steps=4,
                                kv_pages=40, prefix_sharing=sharing)
            ids, done = _run_workload(eng, workload, waves=2)
            return eng, [done[r].tokens.tolist() for r in sorted(ids)]

        eng_off, off = run(False)
        eng_on, on = run(True)
        assert on == off
        assert eng_off.stats.n_prefix_hits == 0
        assert eng_on.stats.n_zero_copy_hits > 0

    def test_eos_freeze_with_paged_hits_matches_generate(self):
        cfg = _cfg()
        params = init_params(cfg, seed=5)
        rng = np.random.default_rng(2)
        shared = rng.integers(0, cfg.vocab, 32).astype(np.int32)
        prompts = [np.concatenate(
            [shared, rng.integers(0, cfg.vocab, k)]).astype(np.int32)
            for k in (3, 5, 8)]
        steps = 16
        free = [np.asarray(generate(
            params, jnp.asarray(p[None], jnp.int32), steps, cfg))[0]
            for p in prompts]
        eos = int(free[0][steps // 2])
        eng = ServingEngine(params, cfg, batch=2, round_steps=4,
                            eos_id=eos, kv_pages=30)
        ids = {eng.submit(p, steps): p for p in prompts}
        done = {r.request_id: r for r in eng.run()}
        fired = 0
        for rid, p in ids.items():
            ref = np.asarray(generate(
                params, jnp.asarray(p[None], jnp.int32), steps, cfg,
                eos_id=eos))[0]
            np.testing.assert_array_equal(done[rid].tokens, ref)
            fired += int((ref == eos).any())
        assert fired >= 1 and eng.stats.n_prefix_hits >= 1

    def test_page_pressure_waits_and_evicts_exactly(self):
        # A pool too small for the whole batch: reservations that don't
        # fit leave requests queued (push_front, no drops), stored
        # prefixes are evicted under pressure, and outputs stay
        # bit-identical to the sharing-off run — no use-after-evict, no
        # stale alias.
        cfg = _cfg()
        params = init_params(cfg, seed=6)
        rng = np.random.default_rng(10)
        shares = [rng.integers(0, cfg.vocab, 32).astype(np.int32)
                  for _ in range(3)]
        workload = []
        for rep in range(2):
            for j, sh in enumerate(shares):
                tail = rng.integers(0, cfg.vocab, 3 + rep + j)
                workload.append(
                    (np.concatenate([sh, tail]).astype(np.int32),
                     3 + rep + j))

        def run(sharing):
            # 5 pages: one 3-page reservation + one stored 2-page
            # prefix exhaust the pool — every admission fights for it.
            eng = ServingEngine(params, cfg, batch=2, round_steps=6,
                                kv_pages=5, prefix_sharing=sharing)
            ids = [eng.submit(p, s) for p, s in workload]
            done = {r.request_id: r for r in eng.run()}
            return eng, [done[r].tokens.tolist() for r in ids]

        eng_off, off = run(False)
        eng_on, on = run(True)
        assert on == off
        assert eng_on.stats.n_completed == len(workload)
        # The pressure was real: failed reservations and evictions.
        assert eng_on.page_pool.alloc_failures > 0
        assert eng_on.prefix_index.evictions > 0
        # Everything came back: only stored entries hold pages now.
        pool = eng_on.page_pool
        assert pool.n_used == sum(
            e.length // PAGE for e in eng_on.prefix_index._entries
            .values())


class TestZeroCopyAliasing:
    def test_aliased_pages_are_bytewise_immutable(self):
        # Store a prefix, snapshot its pages' device bytes, then run
        # several hit admissions that DECODE OVER the aliased pages —
        # the stored bytes must not move (aliased pages are read-only
        # by the reservation discipline: decode writes land at page
        # index >= hit/PAGE, which is never aliased).
        cfg = _cfg()
        params = init_params(cfg, seed=3)
        rng = np.random.default_rng(4)
        shared = rng.integers(0, cfg.vocab, 48).astype(np.int32)
        eng = ServingEngine(params, cfg, batch=2, round_steps=4,
                            kv_pages=30)
        eng.submit(np.concatenate(
            [shared, rng.integers(0, cfg.vocab, 5)]).astype(np.int32), 4)
        eng.run()
        (entry,) = eng.prefix_index._entries.values()
        pages = np.asarray(entry.pages)

        def snap():
            # np.array: the pool is a donated buffer (device_get's CPU
            # zero-copy view would disable donation — marlint
            # donation-fetch).
            return [
                {name: np.array(layer[name][pages])
                 for name in layer}
                for layer in eng.page_pool.pages]

        before = snap()
        for i in range(3):
            tail = rng.integers(0, cfg.vocab, 4 + i)
            eng.submit(np.concatenate([shared, tail]).astype(np.int32),
                       5)
        eng.run()
        assert eng.stats.n_zero_copy_hits >= 3
        after = snap()
        for la, lb in zip(before, after):
            for name in la:
                np.testing.assert_array_equal(la[name], lb[name])

    def test_ledgers_and_debug_surface(self):
        cfg = _cfg()
        params = init_params(cfg, seed=0)
        rng = np.random.default_rng(9)
        eng = ServingEngine(params, cfg, batch=3, round_steps=4,
                            kv_pages=40)
        _run_workload(eng, _shared_prefix_workload(cfg, rng), waves=2)
        summ = eng.stats.summary()
        assert summ["admission_copy_bytes"] == 0
        assert summ["zero_copy_hits"] == eng.stats.n_prefix_hits > 0
        assert summ["kv_pages"]["kv_pages_total"] == 40
        snap = eng.debug_snapshot()
        assert snap["kv_pages"]["kv_pages_used"] > 0
        assert snap["prefix_index"]["prefix_stores"] > 0
        # Registry mirrors (the observability satellite).
        ms = eng.metrics.snapshot()
        assert ms["gauges"]["serving_kv_pages_total"] == 40
        assert ms["gauges"]["serving_kv_pages_used"] > 0
        assert "serving_kv_page_fragmentation" in ms["gauges"]
        assert ms["counters"]["serving_kv_zero_copy_hits_total"] > 0
        # Round events narrate occupancy for the offline analyzer.
        rounds = eng.runlog.events("round")
        assert rounds and all("pages_used" in e for e in rounds)
        start = eng.runlog.events("engine_start")[-1]
        assert start["kv_pages"] == 40 and start["prefix_sharing"]


class TestPagedNoRebuild:
    def test_donation_pointers_and_compile_counts(self):
        # vocab=55 makes the cfg unique so jit-cache deltas are exact.
        cfg = _cfg(vocab=55)
        params = init_params(cfg, seed=8)
        rng = np.random.default_rng(3)
        shared = rng.integers(0, cfg.vocab, 32).astype(np.int32)
        eng = ServingEngine(params, cfg, batch=3, round_steps=4,
                            kv_pages=40)

        def submit_two():
            for _ in range(2):
                tail = rng.integers(0, cfg.vocab, 6)
                eng.submit(np.concatenate(
                    [shared, tail]).astype(np.int32), 5)

        round0 = _decode_round_paged._cache_size()
        chunk0 = prefill_chunk_into_row_paged._cache_size()
        # Warmup twice: miss-path chunks, then the hit path (same chunk
        # buckets — a hit changes start/length operands, not shapes).
        for _ in range(2):
            submit_two()
            eng.run()
        assert eng.stats.n_prefix_hits >= 2
        # Exactly 1 round + 2 chunk compiles (interior + final bucket);
        # no copy compile exists in the paged engine.
        assert _decode_round_paged._cache_size() == round0 + 1
        assert prefill_chunk_into_row_paged._cache_size() == chunk0 + 2

        def pointers():
            ptrs = [eng._buf.unsafe_buffer_pointer()]
            for layer in eng.page_pool.pages:
                ptrs += [v.unsafe_buffer_pointer()
                         for v in layer.values()]
            return ptrs

        before = pointers()
        for _ in range(3):
            submit_two()
            eng.run()
        assert eng.stats.n_zero_copy_hits >= 8
        assert pointers() == before
        assert _decode_round_paged._cache_size() == round0 + 1
        assert prefill_chunk_into_row_paged._cache_size() == chunk0 + 2


class TestCapacity:
    def test_strictly_more_concurrent_sequences_per_pool_byte(self):
        # Equal pool bytes: 2 contiguous rows at max_len == 2 * 10
        # pages. The row cache holds exactly 2 concurrent sequences;
        # the paged pool holds every one of 6 short requests at once —
        # reservation-exact sizing + zero-copy sharing is the capacity
        # multiplier the bench line sweeps.
        cfg = _cfg()  # max_len=160 -> 10 chunks/row
        params = init_params(cfg, seed=0)
        rng = np.random.default_rng(0)
        shared = rng.integers(0, cfg.vocab, 32).astype(np.int32)
        n_pages = 2 * (cfg.max_len // PAGE)  # == 2 row-equivalents
        eng = ServingEngine(params, cfg, batch=8, round_steps=1,
                            kv_pages=n_pages,
                            prefill_chunks_per_round=4)
        for i in range(6):
            tail = rng.integers(0, cfg.vocab, 4)
            # prompt 36 + steps 8 -> 3 pages each (all admitted in one
            # wave, before any store lands): 18 <= 20 pages — 6
            # concurrent where the row cache fits 2. Steady-state
            # sharing (the zero-copy tests) pushes further still.
            eng.submit(np.concatenate([shared, tail]).astype(np.int32),
                       8)
        eng.step()  # one admission round: everything placed
        assert eng.slots.n_occupied + len(eng._prefilling) == 6 > 2
        assert eng.page_pool.alloc_failures == 0
        eng.run()
        assert eng.stats.n_completed == 6

    def test_host_tier_keeps_5x_stored_prefixes_hittable(self):
        """ISSUE 16's capacity done-bar at unit scope (bench.py
        --config serving_host_kv sweeps the same drive): at EQUAL
        device bytes, attaching the host tier keeps >= 5x as many
        stored prefixes HITTABLE — resident entries answer from device,
        spilled ones restore from the host payload — where the
        tier-less index is bound by pool capacity alone."""
        cfg = _cfg(max_len=64)
        n_per = 2                 # 32-token prefixes -> 2 pages each
        budget_pages = 2 * n_per  # device fits exactly 2 resident
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, cfg.vocab, n_per * PAGE + 4)
                   .astype(np.int32) for _ in range(16)]

        def hittable(tiered):
            reg = MetricsRegistry()
            pool = PagePool(cfg, budget_pages, registry=reg)
            tier = HostKVTier(pool, budget_bytes=5 * pool.pool_bytes,
                              registry=reg) if tiered else None
            idx = PagedPrefixIndex(pool, registry=reg, host_tier=tier)
            for p in prompts:  # one admit+store+retire per prefix
                pages = pool.alloc(n_per)
                if pages is None:
                    idx.evict_until_free(n_per)
                    pages = pool.alloc(n_per)
                idx.store(p, pages)
                pool.unref(pages)
            n = 0
            for p in prompts:
                _, hit, sp, _ = idx.lookup_candidates(p)
                if hit:
                    n += 1
                elif (sp is not None and tier is not None
                      and tier.fetch(idx.host_key_of(sp)) is not None):
                    n += 1  # restorable: the payload is really there
            return n

        plain, tiered = hittable(False), hittable(True)
        assert plain == budget_pages // n_per  # device-bound: 2
        assert tiered >= 5 * plain
