"""All-to-all (Ulysses) sequence parallelism vs a NumPy/JAX oracle.

Golden-value pattern of the reference suite (DistributedMatrixSuite.scala:
distributed op -> toBreeze -> compare): here the distributed op is head-
sharded attention over the 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu.parallel import (
    ring_self_attention,
    sequence_parallel_attention,
    ulysses_self_attention,
)


def oracle_mha(q, k, v, scale=None, causal=False):
    """(S, H, D) multi-head attention in float64 NumPy."""
    q, k, v = (np.asarray(x, np.float64) for x in (q, k, v))
    s, h, d = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    out = np.zeros_like(v)
    for hh in range(h):
        logits = scale * (q[:, hh] @ k[:, hh].T)
        if causal:
            mask = np.tril(np.ones((s, s), bool))
            logits = np.where(mask, logits, -np.inf)
        logits -= logits.max(axis=1, keepdims=True)
        p = np.exp(logits)
        out[:, hh] = (p / p.sum(axis=1, keepdims=True)) @ v[:, hh]
    return out


def rand_qkv(seed, s, h, d):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (s, h, d), jnp.float64) for k in ks)


class TestUlyssesAttention:
    def test_matches_oracle(self, mesh):
        q, k, v = rand_qkv(0, 64, 8, 16)
        out = ulysses_self_attention(q, k, v, mesh=mesh)
        np.testing.assert_allclose(
            np.asarray(out), oracle_mha(q, k, v), rtol=1e-10, atol=1e-10
        )

    def test_causal(self, mesh):
        q, k, v = rand_qkv(1, 32, 16, 8)
        out = ulysses_self_attention(q, k, v, mesh=mesh, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), oracle_mha(q, k, v, causal=True), rtol=1e-10, atol=1e-10
        )

    def test_output_stays_sequence_sharded(self, mesh):
        q, k, v = rand_qkv(2, 64, 8, 4)
        out = ulysses_self_attention(q, k, v, mesh=mesh)
        specs = out.sharding.spec
        assert specs[0] is not None and specs[1] is None

    def test_rejects_indivisible(self, mesh):
        q, k, v = rand_qkv(3, 60, 8, 4)
        with pytest.raises(ValueError, match="sequence length"):
            ulysses_self_attention(q, k, v, mesh=mesh)
        q, k, v = rand_qkv(4, 64, 6, 4)
        with pytest.raises(ValueError, match="head count"):
            ulysses_self_attention(q, k, v, mesh=mesh)


class TestSequenceParallelDispatch:
    def test_auto_picks_all_to_all_when_heads_divide(self, mesh):
        q, k, v = rand_qkv(5, 32, 8, 8)
        out = sequence_parallel_attention(q, k, v, mesh=mesh, strategy="auto")
        np.testing.assert_allclose(
            np.asarray(out), oracle_mha(q, k, v), rtol=1e-10, atol=1e-10
        )

    def test_auto_falls_back_to_ring_for_odd_heads(self, mesh):
        # 3 heads don't divide 8 devices -> per-head ring passes.
        q, k, v = rand_qkv(6, 32, 3, 8)
        out = sequence_parallel_attention(q, k, v, mesh=mesh, strategy="auto")
        np.testing.assert_allclose(
            np.asarray(out), oracle_mha(q, k, v), rtol=1e-10, atol=1e-10
        )

    def test_ring_and_all_to_all_agree(self, mesh):
        q, k, v = rand_qkv(7, 64, 8, 8)
        a = sequence_parallel_attention(q, k, v, mesh=mesh, strategy="all_to_all")
        b = sequence_parallel_attention(q, k, v, mesh=mesh, strategy="ring")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-9, atol=1e-9)

    def test_causal_ring_3d(self, mesh):
        q, k, v = rand_qkv(8, 32, 2, 8)
        out = sequence_parallel_attention(
            q, k, v, mesh=mesh, strategy="ring", causal=True
        )
        np.testing.assert_allclose(
            np.asarray(out), oracle_mha(q, k, v, causal=True), rtol=1e-9, atol=1e-9
        )

    def test_unknown_strategy(self, mesh):
        q, k, v = rand_qkv(9, 32, 8, 8)
        with pytest.raises(ValueError, match="unknown"):
            sequence_parallel_attention(q, k, v, mesh=mesh, strategy="spiral")

    def test_auto_cross_attention_falls_back_to_ring(self, mesh):
        # kv length != q length: all_to_all can't express it, ring streams it.
        q, _, _ = rand_qkv(10, 32, 8, 8)
        _, k, v = rand_qkv(11, 64, 8, 8)
        out = sequence_parallel_attention(q, k, v, mesh=mesh, strategy="auto")
        scale = 1.0 / np.sqrt(8)
        qn, kn, vn = (np.asarray(x, np.float64) for x in (q, k, v))
        want = np.zeros((32, 8, 8))
        for hh in range(8):
            logits = scale * (qn[:, hh] @ kn[:, hh].T)
            logits -= logits.max(axis=1, keepdims=True)
            p = np.exp(logits)
            want[:, hh] = (p / p.sum(axis=1, keepdims=True)) @ vn[:, hh]
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-9, atol=1e-9)

    def test_multihead_ring_matches_per_head_2d(self, mesh):
        # The vmapped multi-head ring path must agree with independent 2-D
        # ring passes per head (the previous implementation's semantics).
        q, k, v = rand_qkv(12, 32, 3, 8)
        out = sequence_parallel_attention(q, k, v, mesh=mesh, strategy="ring")
        per_head = np.stack(
            [
                np.asarray(ring_self_attention(q[:, h], k[:, h], v[:, h], mesh=mesh))
                for h in range(3)
            ],
            axis=1,
        )
        np.testing.assert_allclose(np.asarray(out), per_head, rtol=1e-12, atol=1e-12)


def oracle_gqa(q, k, v, causal=False):
    """GQA oracle: broadcast kv heads, then the MHA oracle."""
    group = q.shape[1] // k.shape[1]
    return oracle_mha(q, np.repeat(np.asarray(k), group, axis=1),
                      np.repeat(np.asarray(v), group, axis=1), causal=causal)


class TestSequenceParallelGQA:
    """GQA/MQA through BOTH SP engines: the ring streams the reduced K/V
    stripes (per-kv-head pipelines shared across the q-head group — ICI
    traffic keeps the group-factor shrink); all_to_all shards kv heads when
    they divide the mesh, with per-device grouping alignment preserved by
    contiguous head chunks."""

    def _rand(self, seed, s, h, hk, d):
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(ks[0], (s, h, d), jnp.float64)
        k = jax.random.normal(ks[1], (s, hk, d), jnp.float64)
        v = jax.random.normal(ks[2], (s, hk, d), jnp.float64)
        return q, k, v

    @pytest.mark.parametrize("h,hk", [(16, 8), (8, 1)])  # GQA and MQA
    def test_ring_gqa_matches_oracle(self, mesh, h, hk):
        q, k, v = self._rand(0, 32, h, hk, 8)
        out = ring_self_attention(q, k, v, mesh=mesh, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), oracle_gqa(q, k, v, causal=True),
            rtol=1e-10, atol=1e-10)

    def test_ulysses_gqa_matches_oracle(self, mesh):
        # kv_heads divisible by the 8-device mesh.
        q, k, v = self._rand(1, 32, 16, 8, 8)
        out = ulysses_self_attention(q, k, v, mesh=mesh, local_kernel="xla")
        np.testing.assert_allclose(
            np.asarray(out), oracle_gqa(q, k, v), rtol=1e-10, atol=1e-10)

    def test_auto_routes_gqa_by_kv_divisibility(self, mesh):
        n_dev = len(mesh.devices.flat)
        # kv heads NOT divisible by the mesh -> ring handles it fine.
        q, k, v = self._rand(2, 4 * n_dev, 2 * n_dev, 2, 8)
        out = sequence_parallel_attention(q, k, v, mesh=mesh, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), oracle_gqa(q, k, v, causal=True),
            rtol=1e-10, atol=1e-10)
        # kv heads divisible -> both engines agree on the same input, and
        # AUTO must actually route to all_to_all (spied): a dead
        # divisibility check silently re-routing GQA to ring is the
        # regression this catches.
        q, k, v = self._rand(3, 4 * n_dev, 2 * n_dev, n_dev, 8)
        a = sequence_parallel_attention(q, k, v, mesh=mesh,
                                        strategy="all_to_all",
                                        causal=True)
        r = sequence_parallel_attention(q, k, v, mesh=mesh, strategy="ring",
                                        causal=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-10, atol=1e-10)
        import marlin_tpu.parallel.ulysses as ul
        called = []
        real = ul.ulysses_self_attention
        ul.ulysses_self_attention = (
            lambda *a_, **k_: (called.append(1), real(*a_, **k_))[1])
        try:
            auto = sequence_parallel_attention(q, k, v, mesh=mesh,
                                               causal=True)
        finally:
            ul.ulysses_self_attention = real
        assert called, "auto did not route divisible GQA to all_to_all"
        np.testing.assert_allclose(np.asarray(auto), np.asarray(a),
                                   rtol=1e-10, atol=1e-10)

    def test_ulysses_rejects_unshardable_kv_heads(self, mesh):
        q, k, v = self._rand(4, 32, 16, 2, 8)  # 2 kv heads, 8 devices
        with pytest.raises(ValueError, match="ring engine"):
            ulysses_self_attention(q, k, v, mesh=mesh)

    def test_ring_gqa_grads_match_dense(self, mesh):
        # Training path: SP-GQA gradients equal the dense broadcast-heads
        # formulation.
        q, k, v = self._rand(5, 16, 4, 2, 8)

        def sp_loss(q, k, v):
            return jnp.sum(ring_self_attention(
                q, k, v, mesh=mesh, causal=True) ** 2)

        def dense_loss(q, k, v):
            kk = jnp.repeat(k, 2, axis=1)
            vv = jnp.repeat(v, 2, axis=1)
            out = oracle_jnp(q, kk, vv)
            return jnp.sum(out ** 2)

        def oracle_jnp(q, k, v):
            s, h, d = q.shape
            sc = 1.0 / np.sqrt(d)
            logits = jnp.einsum("shd,thd->hst", q, k) * sc
            mask = jnp.tril(jnp.ones((s, s), bool))
            logits = jnp.where(mask[None], logits, -jnp.inf)
            p = jax.nn.softmax(logits, axis=-1)
            return jnp.einsum("hst,thd->shd", p, v)

        gs = jax.grad(sp_loss, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gs, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-8, atol=1e-8)


class TestBF16Inputs:
    """Mixed-precision rollout contract: the SP engines accumulate >= f32
    internally (softmax, PV sums), so bf16 q/k/v must track the f64 oracle
    to bf16 IO tolerance — not bf16-accumulation error."""

    @pytest.mark.parametrize("strategy", ["ring", "all_to_all"])
    def test_bf16_tracks_oracle(self, strategy):
        s, h, d = 32, 8, 16
        ks = jax.random.split(jax.random.PRNGKey(5), 3)
        q, k, v = (jax.random.normal(kk, (s, h, d), jnp.bfloat16)
                   for kk in ks)
        got = sequence_parallel_attention(q, k, v, causal=True,
                                          strategy=strategy)
        assert got.dtype == jnp.bfloat16
        ref = oracle_mha(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got, np.float64), ref, rtol=0.05, atol=0.05)
