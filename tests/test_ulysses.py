"""All-to-all (Ulysses) sequence parallelism vs a NumPy/JAX oracle.

Golden-value pattern of the reference suite (DistributedMatrixSuite.scala:
distributed op -> toBreeze -> compare): here the distributed op is head-
sharded attention over the 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import marlin_tpu as mt
from marlin_tpu.parallel import (
    ring_self_attention,
    sequence_parallel_attention,
    ulysses_self_attention,
)


def oracle_mha(q, k, v, scale=None, causal=False):
    """(S, H, D) multi-head attention in float64 NumPy."""
    q, k, v = (np.asarray(x, np.float64) for x in (q, k, v))
    s, h, d = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    out = np.zeros_like(v)
    for hh in range(h):
        logits = scale * (q[:, hh] @ k[:, hh].T)
        if causal:
            mask = np.tril(np.ones((s, s), bool))
            logits = np.where(mask, logits, -np.inf)
        logits -= logits.max(axis=1, keepdims=True)
        p = np.exp(logits)
        out[:, hh] = (p / p.sum(axis=1, keepdims=True)) @ v[:, hh]
    return out


def rand_qkv(seed, s, h, d):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (s, h, d), jnp.float64) for k in ks)


class TestUlyssesAttention:
    def test_matches_oracle(self, mesh):
        q, k, v = rand_qkv(0, 64, 8, 16)
        out = ulysses_self_attention(q, k, v, mesh=mesh)
        np.testing.assert_allclose(
            np.asarray(out), oracle_mha(q, k, v), rtol=1e-10, atol=1e-10
        )

    def test_causal(self, mesh):
        q, k, v = rand_qkv(1, 32, 16, 8)
        out = ulysses_self_attention(q, k, v, mesh=mesh, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), oracle_mha(q, k, v, causal=True), rtol=1e-10, atol=1e-10
        )

    def test_output_stays_sequence_sharded(self, mesh):
        q, k, v = rand_qkv(2, 64, 8, 4)
        out = ulysses_self_attention(q, k, v, mesh=mesh)
        specs = out.sharding.spec
        assert specs[0] is not None and specs[1] is None

    def test_rejects_indivisible(self, mesh):
        q, k, v = rand_qkv(3, 60, 8, 4)
        with pytest.raises(ValueError, match="sequence length"):
            ulysses_self_attention(q, k, v, mesh=mesh)
        q, k, v = rand_qkv(4, 64, 6, 4)
        with pytest.raises(ValueError, match="head count"):
            ulysses_self_attention(q, k, v, mesh=mesh)


class TestSequenceParallelDispatch:
    def test_auto_picks_all_to_all_when_heads_divide(self, mesh):
        q, k, v = rand_qkv(5, 32, 8, 8)
        out = sequence_parallel_attention(q, k, v, mesh=mesh, strategy="auto")
        np.testing.assert_allclose(
            np.asarray(out), oracle_mha(q, k, v), rtol=1e-10, atol=1e-10
        )

    def test_auto_falls_back_to_ring_for_odd_heads(self, mesh):
        # 3 heads don't divide 8 devices -> per-head ring passes.
        q, k, v = rand_qkv(6, 32, 3, 8)
        out = sequence_parallel_attention(q, k, v, mesh=mesh, strategy="auto")
        np.testing.assert_allclose(
            np.asarray(out), oracle_mha(q, k, v), rtol=1e-10, atol=1e-10
        )

    def test_ring_and_all_to_all_agree(self, mesh):
        q, k, v = rand_qkv(7, 64, 8, 8)
        a = sequence_parallel_attention(q, k, v, mesh=mesh, strategy="all_to_all")
        b = sequence_parallel_attention(q, k, v, mesh=mesh, strategy="ring")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-9, atol=1e-9)

    def test_causal_ring_3d(self, mesh):
        q, k, v = rand_qkv(8, 32, 2, 8)
        out = sequence_parallel_attention(
            q, k, v, mesh=mesh, strategy="ring", causal=True
        )
        np.testing.assert_allclose(
            np.asarray(out), oracle_mha(q, k, v, causal=True), rtol=1e-9, atol=1e-9
        )

    def test_unknown_strategy(self, mesh):
        q, k, v = rand_qkv(9, 32, 8, 8)
        with pytest.raises(ValueError, match="unknown"):
            sequence_parallel_attention(q, k, v, mesh=mesh, strategy="spiral")

    def test_auto_cross_attention_falls_back_to_ring(self, mesh):
        # kv length != q length: all_to_all can't express it, ring streams it.
        q, _, _ = rand_qkv(10, 32, 8, 8)
        _, k, v = rand_qkv(11, 64, 8, 8)
        out = sequence_parallel_attention(q, k, v, mesh=mesh, strategy="auto")
        scale = 1.0 / np.sqrt(8)
        qn, kn, vn = (np.asarray(x, np.float64) for x in (q, k, v))
        want = np.zeros((32, 8, 8))
        for hh in range(8):
            logits = scale * (qn[:, hh] @ kn[:, hh].T)
            logits -= logits.max(axis=1, keepdims=True)
            p = np.exp(logits)
            want[:, hh] = (p / p.sum(axis=1, keepdims=True)) @ vn[:, hh]
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-9, atol=1e-9)

    def test_multihead_ring_matches_per_head_2d(self, mesh):
        # The vmapped multi-head ring path must agree with independent 2-D
        # ring passes per head (the previous implementation's semantics).
        q, k, v = rand_qkv(12, 32, 3, 8)
        out = sequence_parallel_attention(q, k, v, mesh=mesh, strategy="ring")
        per_head = np.stack(
            [
                np.asarray(ring_self_attention(q[:, h], k[:, h], v[:, h], mesh=mesh))
                for h in range(3)
            ],
            axis=1,
        )
        np.testing.assert_allclose(np.asarray(out), per_head, rtol=1e-12, atol=1e-12)
