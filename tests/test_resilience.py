"""Crash-resume tests for the checkpointed iteration wrapper, including the
crash-atomicity and stale-state contracts."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from marlin_tpu.utils import resilience
from marlin_tpu.utils.resilience import clear, latest_step, run_with_checkpoints

STATE0 = lambda: {"x": jnp.zeros(3)}


def _step(state, i):
    return {"x": state["x"] + (i + 1)}


class TestRunWithCheckpoints:
    def test_uninterrupted(self, tmp_path):
        state, ran = run_with_checkpoints(_step, STATE0(), 10, str(tmp_path / "c"), every=4)
        assert ran == 10
        np.testing.assert_allclose(np.asarray(state["x"]), 55.0)

    def test_crash_and_resume_matches(self, tmp_path):
        path = str(tmp_path / "c")

        class Crash(Exception):
            pass

        def crashing(state, i):
            if i == 6:
                raise Crash()
            return _step(state, i)

        with pytest.raises(Crash):
            run_with_checkpoints(crashing, STATE0(), 10, path, every=3)
        assert latest_step(path, like=STATE0()) == 6  # checkpoints 3, 6 completed

        # Resume runs only the remaining steps and reaches the same result.
        state, ran = run_with_checkpoints(_step, STATE0(), 10, path, every=3)
        assert ran == 4
        np.testing.assert_allclose(np.asarray(state["x"]), 55.0)

    def test_resume_false_clears_stale_state(self, tmp_path):
        path = str(tmp_path / "c")
        run_with_checkpoints(_step, STATE0(), 10, path, every=5)  # run A completes
        # Fresh run crashes before its first checkpoint...
        state, ran = run_with_checkpoints(_step, STATE0(), 0, path, every=5, resume=False)
        assert ran == 0
        # ...and a retry with resume=True must NOT pick up run A's state.
        assert latest_step(path, like=STATE0()) is None
        state, ran = run_with_checkpoints(_step, STATE0(), 4, path, every=2)
        assert ran == 4
        np.testing.assert_allclose(np.asarray(state["x"]), 10.0)

    def test_completed_run_resumes_to_noop(self, tmp_path):
        path = str(tmp_path / "c")
        run_with_checkpoints(_step, STATE0(), 5, path, every=2)
        state, ran = run_with_checkpoints(_step, STATE0(), 5, path, every=2)
        assert ran == 0
        np.testing.assert_allclose(np.asarray(state["x"]), 15.0)

    def test_crash_mid_save_keeps_previous_checkpoint(self, tmp_path, monkeypatch):
        path = str(tmp_path / "c")
        run_with_checkpoints(_step, STATE0(), 4, path, every=4)  # checkpoint @4

        # Simulate a crash inside the NEXT save, after the side-dir write
        # begins but before the swap: the step-4 checkpoint must survive.
        real_save = resilience.ckpt.save_pytree

        def dying_save(tree, p):
            real_save(tree, p)
            raise RuntimeError("power loss")

        monkeypatch.setattr(resilience.ckpt, "save_pytree", dying_save)
        with pytest.raises(RuntimeError):
            run_with_checkpoints(_step, STATE0(), 8, path, every=4)
        monkeypatch.setattr(resilience.ckpt, "save_pytree", real_save)

        assert latest_step(path, like=STATE0()) == 4
        state, ran = run_with_checkpoints(_step, STATE0(), 8, path, every=4)
        assert ran == 4
        np.testing.assert_allclose(np.asarray(state["x"]), 36.0)

    def test_restore_preserves_sharding(self, tmp_path):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        import marlin_tpu as mt

        mesh = mt.default_mesh()
        sh = NamedSharding(mesh, P(("mr", "mc")))
        path = str(tmp_path / "c")
        init = {"x": jax.device_put(jnp.zeros(16), sh)}
        run_with_checkpoints(lambda s, i: {"x": s["x"] + 1}, init, 2, path, every=1)
        state, ran = run_with_checkpoints(lambda s, i: {"x": s["x"] + 1}, init, 4, path, every=1)
        assert ran == 2
        assert state["x"].sharding.is_equivalent_to(sh, state["x"].ndim)
        np.testing.assert_allclose(np.asarray(state["x"]), 4.0)


class TestTransformerCrashResume:
    def test_interrupted_training_resumes_to_identical_params(self, tmp_path):
        # Integration of the recovery subsystem with the flagship model:
        # crash mid-training, resume from the checkpoint, and land on
        # bit-identical params to an uninterrupted run (deterministic steps
        # + atomic rename-swap checkpoints).
        import jax
        import jax.numpy as jnp
        import numpy as np

        from marlin_tpu.models import TransformerConfig, init_params, train_step

        cfg = TransformerConfig(vocab=17, d_model=16, n_heads=2, n_layers=1,
                                d_ff=32, max_len=8)
        tok = jnp.asarray(
            np.random.default_rng(0).integers(0, 17, (2, 8)), jnp.int32)
        tgt = jnp.roll(tok, -1, axis=1)
        jstep = jax.jit(train_step, static_argnames="cfg")

        def step(params, i):
            _, params = jstep(params, tok, tgt, cfg=cfg)
            return params

        path = str(tmp_path / "t")
        ref, _ = run_with_checkpoints(
            step, init_params(cfg, seed=0), 6, path + "_ref", every=2)

        calls = {"n": 0}

        def crashing(params, i):
            if i == 4:
                calls["n"] += 1
                if calls["n"] == 1:
                    raise RuntimeError("injected crash at step 4")
            return step(params, i)

        try:
            run_with_checkpoints(
                crashing, init_params(cfg, seed=0), 6, path, every=2)
        except RuntimeError:
            pass
        got, ran = run_with_checkpoints(
            crashing, init_params(cfg, seed=0), 6, path, every=2)
        assert ran == 2  # resumed from the step-4 checkpoint
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(ref)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
