"""Crash-resume tests for the checkpointed iteration wrapper."""

import jax.numpy as jnp
import numpy as np
import pytest

from marlin_tpu.utils.resilience import latest_step, run_with_checkpoints


def _step(state, i):
    return {"x": state["x"] + (i + 1)}


class TestRunWithCheckpoints:
    def test_uninterrupted(self, tmp_path):
        state, ran = run_with_checkpoints(
            _step, {"x": jnp.zeros(3)}, 10, str(tmp_path / "c"), every=4
        )
        assert ran == 10
        np.testing.assert_allclose(np.asarray(state["x"]), 55.0)

    def test_crash_and_resume_matches(self, tmp_path):
        path = str(tmp_path / "c")

        class Crash(Exception):
            pass

        def crashing(state, i):
            if i == 6:
                raise Crash()
            return _step(state, i)

        with pytest.raises(Crash):
            run_with_checkpoints(crashing, {"x": jnp.zeros(3)}, 10, path, every=3)
        assert latest_step(path) == 6  # checkpoints at 3 and 6 completed

        # Resume runs only the remaining steps and reaches the same result.
        state, ran = run_with_checkpoints(_step, {"x": jnp.zeros(3)}, 10, path, every=3)
        assert ran == 4
        np.testing.assert_allclose(np.asarray(state["x"]), 55.0)

    def test_resume_disabled_restarts(self, tmp_path):
        path = str(tmp_path / "c")
        run_with_checkpoints(_step, {"x": jnp.zeros(1)}, 4, path, every=2)
        _, ran = run_with_checkpoints(
            _step, {"x": jnp.zeros(1)}, 4, path, every=2, resume=False
        )
        assert ran == 4

    def test_completed_run_resumes_to_noop(self, tmp_path):
        path = str(tmp_path / "c")
        run_with_checkpoints(_step, {"x": jnp.zeros(1)}, 5, path, every=2)
        state, ran = run_with_checkpoints(_step, {"x": jnp.zeros(1)}, 5, path, every=2)
        assert ran == 0
        np.testing.assert_allclose(np.asarray(state["x"]), 15.0)
