"""Continuous-batching serving engine tests (marlin_tpu/serving/).

The three acceptance claims, each pinned mechanically:

* EXACTNESS — every request's emitted tokens are BIT-exact vs a B=1
  ``generate`` run of the same prompt (greedy), for plain / rope+GQA /
  int8-cache configs, regardless of which rows its neighbors occupied,
  when it was admitted, or what was swapped in next to it mid-stream
  (per-row independence + the 16-bucket admission prefill,
  serving/slots.py module docstring).
* RECLAIM — on a skewed workload, continuous batching completes >= 1.3x
  the requests a static batcher completes in the same number of decode
  iterations (simulated rounds: iteration counts, not wall-clock, so CI
  noise cannot vote), and the reclaimed-FLOPs ledger is positive.
* NO RECOMPILE / NO REBUILD — admissions and rounds hit exactly one
  compile each (plus one per distinct prompt 16-bucket), and the cache
  and token buffer stay in the SAME device buffers (donation aliasing)
  across every swap — the test_decode_donation.py contract extended to
  the serving loop.

The PR-4 admission disciplines extend these pins in
tests/test_prefix_cache.py: the chunked path's B=1-generate exactness,
cache-on-vs-cache-off bitwise identity (incl. eviction pressure),
prefix-hit pointer stability and compile bounds, and the sampled-path
per-request key-stream invariance (greedy=False).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from marlin_tpu.models import TransformerConfig, generate, init_params
from marlin_tpu.obs.watch import no_transfers
from marlin_tpu.serving import (AdmissionQueue, QueueClosed, QueueFull,
                                Request, ServingEngine, SlotManager,
                                pad_prompt_len, static_completed_at_budget,
                                static_schedule_iters)
from marlin_tpu.serving.engine import _decode_round
from marlin_tpu.serving.slots import prefill_into_row


def _cfg(**kw):
    base = dict(vocab=48, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                max_len=96)
    base.update(kw)
    return TransformerConfig(**base)


def _req(rid=0, steps=4, prompt_len=4, **kw):
    return Request(request_id=rid, steps=steps,
                   prompt=np.zeros((prompt_len,), np.int32), **kw)


class TestAdmissionQueue:
    def test_fifo_and_backpressure(self):
        q = AdmissionQueue(max_pending=2)
        q.submit(_req(0))
        q.submit(_req(1))
        with pytest.raises(QueueFull, match="max_pending"):
            q.submit(_req(2))
        got, expired = q.pop_ready(0)
        assert got.request_id == 0 and not expired
        q.submit(_req(2))  # freed capacity accepts again
        assert q.pop_ready(0)[0].request_id == 1

    def test_close_drains_but_rejects_new(self):
        q = AdmissionQueue()
        q.submit(_req(0))
        q.close()
        with pytest.raises(QueueClosed):
            q.submit(_req(1))
        assert q.pop_ready(0)[0].request_id == 0  # queued work survives

    def test_deadline_expiry_drops_at_pop(self):
        q = AdmissionQueue()
        q.submit(_req(0, deadline_rounds=2))
        q.submit(_req(1))
        got, expired = q.pop_ready(5)  # round 5 > deadline 2
        assert got.request_id == 1
        assert [r.request_id for r in expired] == [0]
        assert expired[0].status == "timeout"


class TestSlotManager:
    def test_acquire_release_cycle(self):
        sm = SlotManager(2)
        a, b = sm.acquire(10), sm.acquire(11)
        assert {a, b} == {0, 1} and sm.n_free == 0
        with pytest.raises(RuntimeError, match="no free slot"):
            sm.acquire(12)
        sm.release(a)
        assert sm.n_free == 1 and sm.owner_of(a) is None
        with pytest.raises(RuntimeError, match="double free"):
            sm.release(a)
        assert sm.acquire(12) == a  # freed row is reusable

    def test_pad_prompt_len_is_the_16_bucket(self):
        assert [pad_prompt_len(s) for s in (1, 15, 16, 17, 32, 33)] == \
            [16, 16, 16, 32, 32, 48]
        with pytest.raises(ValueError):
            pad_prompt_len(0)


def _run_workload(engine, workload, waves=1):
    """Submit ``workload`` [(prompt, steps), ...] in ``waves`` batches
    with engine steps in between (mid-stream admission), then drain.
    Returns ``(ids, finished)``: {request_id: (prompt, steps)} and the
    finished Request objects by id (the engine TRANSFERS ownership of
    finished requests through step()/run() and drops them from its own
    dict — bounded host memory is part of the serving contract)."""
    ids = {}
    finished = []
    per = -(-len(workload) // waves)
    for w in range(waves):
        for prompt, steps in workload[w * per:(w + 1) * per]:
            ids[engine.submit(prompt, steps)] = (prompt, steps)
        if w + 1 < waves:
            finished += engine.step()  # queue only partly submitted
    finished += engine.run()
    return ids, {r.request_id: r for r in finished}


class TestServingExactness:
    # Tier-1 wall-clock budget (ROADMAP 9): the default variant is the
    # tier-1 representative; the rope/GQA and int8 variants (~15 s of
    # compile each) run under -m slow.
    @pytest.mark.parametrize("kw", [
        {},
        pytest.param({"rope": True, "n_kv_heads": 1},
                     marks=pytest.mark.slow),
        pytest.param({"kv_quant": "int8"}, marks=pytest.mark.slow),
    ])
    def test_outputs_bit_exact_vs_b1_generate(self, kw):
        # Mixed prompt lengths (three distinct 16-buckets) and skewed
        # step counts, submitted in two waves so admissions land while
        # neighbors are mid-decode: every request must emit exactly its
        # own B=1 greedy generate tokens.
        cfg = _cfg(**kw)
        params = init_params(cfg, seed=0)
        eng = ServingEngine(params, cfg, batch=3, round_steps=5)
        rng = np.random.default_rng(7)
        workload = [(rng.integers(0, cfg.vocab, s), steps)
                    for s, steps in ((9, 20), (17, 5), (20, 12), (5, 30),
                                     (33, 7), (12, 18), (6, 3))]
        # The marlint donation-fetch rule's DYNAMIC cousin: the whole
        # serving loop runs under the scoped transfer guard, so an
        # accidental IMPLICIT hot-loop host transfer (a `float(arr)`/
        # `if arr:` slipping into the round path) fails loudly here —
        # the engine's explicit np.array fetches and jnp.asarray feeds
        # stay allowed (obs/watch.no_transfers, docs/static_analysis.md).
        with no_transfers():
            ids, done = _run_workload(eng, workload, waves=3)
        assert eng.stats.n_completed == len(workload)
        assert not eng.requests  # finished work is handed back, not held
        for rid, (prompt, steps) in ids.items():
            ref = np.asarray(generate(
                params, jnp.asarray(prompt[None], jnp.int32), steps,
                cfg))[0]
            np.testing.assert_array_equal(done[rid].tokens, ref,
                                          err_msg=f"request {rid}")

    def test_arrival_pattern_cannot_move_outputs(self):
        # The same workload through different batch sizes and wave
        # splits — different slot assignments, different freeze/swap
        # interleavings — must produce identical per-request tokens
        # (per-row independence is THE serving invariant).
        cfg = _cfg()
        params = init_params(cfg, seed=3)
        rng = np.random.default_rng(11)
        workload = [(rng.integers(0, cfg.vocab, int(s)), int(st))
                    for s, st in zip(rng.integers(4, 30, 8),
                                     rng.integers(2, 24, 8))]
        outs = []
        for batch, waves, rsteps in ((2, 1, 4), (4, 4, 7), (3, 2, 16)):
            eng = ServingEngine(params, cfg, batch=batch,
                                round_steps=rsteps)
            ids, done = _run_workload(eng, workload, waves=waves)
            # Submission order == workload order, so request ids are the
            # workload indices on a fresh engine.
            outs.append([done[rid].tokens.tolist() for rid in sorted(ids)])
        assert outs[0] == outs[1] == outs[2]

    def test_steps_one_at_max_len_boundary_is_exact(self):
        # Regression (PR-2 review): a steps=1 request is COMPLETE at
        # admission (the prefill's first sample is the whole request).
        # Pre-fix, the decode round still appended one extra token; at
        # prompt_len + 1 == max_len the append clamped onto index
        # max_len - 1 and OVERWROTE the real token. Pin the boundary,
        # an off-boundary steps=1, and the zero-useful-work ledger.
        cfg = _cfg()
        params = init_params(cfg, seed=4)
        rng = np.random.default_rng(6)
        eng = ServingEngine(params, cfg, batch=2, round_steps=4)
        prompts = [rng.integers(0, cfg.vocab, cfg.max_len - 1),  # boundary
                   rng.integers(0, cfg.vocab, 9)]               # interior
        ids = [eng.submit(p, 1) for p in prompts]
        done = {r.request_id: r for r in eng.run()}
        for rid, p in zip(ids, prompts):
            ref = np.asarray(generate(
                params, jnp.asarray(p[None], jnp.int32), 1, cfg))[0]
            np.testing.assert_array_equal(done[rid].tokens, ref)
            # No decode iteration was live work for a prefill-complete
            # request — the utilization ledger must not bill any.
            assert done[rid].live_iters == 0
            assert done[rid].emitted == 1

    def test_eos_freeze_matches_generate(self):
        # Pick an eos the model actually emits (greedy attractors make
        # untrained continuations repeat), then pin serving's outputs —
        # eos at its position, eos padding after — against
        # generate(eos_id=...) per request.
        cfg = _cfg()
        params = init_params(cfg, seed=5)
        rng = np.random.default_rng(2)
        prompts = [rng.integers(0, cfg.vocab, s) for s in (8, 13, 21)]
        steps = 16
        free = [np.asarray(generate(
            params, jnp.asarray(p[None], jnp.int32), steps, cfg))[0]
            for p in prompts]
        eos = int(free[0][steps // 2])  # mid-stream token: fires early
        eng = ServingEngine(params, cfg, batch=2, round_steps=4,
                            eos_id=eos)
        ids = {eng.submit(p, steps): p for p in prompts}
        done = {r.request_id: r for r in eng.run()}
        fired = 0
        for rid, p in ids.items():
            ref = np.asarray(generate(
                params, jnp.asarray(p[None], jnp.int32), steps, cfg,
                eos_id=eos))[0]
            np.testing.assert_array_equal(done[rid].tokens, ref)
            fired += int((ref == eos).any())
        assert fired >= 1  # the eos path actually ran
        # The ledger counts tokens actually generated, not the request's
        # step budget: an early-eos request reports emitted < steps and
        # tokens_out sums the honest figure (PR-2 review finding).
        emitted = [done[r].emitted for r in ids]
        assert eng.stats.tokens_out == sum(emitted)
        assert any(e < steps for e in emitted)


class TestServingReclaim:
    def test_skewed_workload_beats_static_by_1_3x(self):
        # Skewed arrivals: each static FIFO group of 4 carries one
        # straggler, so static batching drains 3 finished rows per
        # group while continuous batching refills them. Equal simulated
        # rounds = equal decode-iteration budget; >= 1.3x completions
        # is the acceptance bar (this workload clears it with margin).
        cfg = _cfg()
        params = init_params(cfg, seed=1)
        rng = np.random.default_rng(4)
        batch = 4
        steps_list = [4, 3, 5, 40, 4, 6, 3, 40, 5, 4, 6, 40]
        workload = [(rng.integers(0, cfg.vocab, int(s)), st)
                    for s, st in zip(rng.integers(4, 16, len(steps_list)),
                                     steps_list)]
        eng = ServingEngine(params, cfg, batch=batch, round_steps=8)
        _run_workload(eng, workload)
        assert eng.stats.n_completed == len(workload)
        # Budget = decode iterations + one per admission prefill
        # (sim_iters: the bias-corrected accounting — a bare iteration
        # count would under-bill continuous requests by their prefill-
        # emitted first token while charging static the full steps).
        budget = eng.stats.sim_iters
        # Static batching on the same FIFO workload, same accounting the
        # bench serving config uses (shared helper in serving/stats.py).
        completed_static = static_completed_at_budget(steps_list, batch,
                                                      budget)
        ratio = eng.stats.n_completed / max(completed_static, 1)
        assert ratio >= 1.3, (ratio, budget, completed_static)

        # The ledger agrees: static spends static_schedule_iters to
        # finish everything; continuous reclaims a positive FLOP count.
        static_iters = static_schedule_iters(steps_list, batch)
        assert budget < static_iters
        assert eng.stats.reclaimed_flops(static_iters=static_iters) > 0
        assert 0.0 < eng.stats.utilization() <= 1.0

    def test_deadline_timeout_and_drain(self):
        cfg = _cfg()
        params = init_params(cfg, seed=2)
        eng = ServingEngine(params, cfg, batch=1, round_steps=2)
        rng = np.random.default_rng(9)
        blocker = eng.submit(rng.integers(0, cfg.vocab, 8), steps=30)
        doomed = eng.submit(rng.integers(0, cfg.vocab, 8), steps=4,
                            deadline_rounds=1)
        eng.close()
        with pytest.raises(QueueClosed):
            eng.submit(rng.integers(0, cfg.vocab, 8), steps=2)
        done = eng.run()  # graceful drain of already-queued work
        by_id = {r.request_id: r for r in done}
        assert by_id[blocker].status == "done"
        assert by_id[doomed].status == "timeout"
        assert by_id[doomed].tokens is None
        assert eng.stats.n_timeout == 1

    def test_submit_guards(self):
        cfg = _cfg()
        eng = ServingEngine(init_params(cfg, seed=0), cfg, batch=1)
        with pytest.raises(ValueError, match="max_len"):
            eng.submit(np.zeros(90, np.int32), steps=10)
        with pytest.raises(ValueError, match="steps"):
            eng.submit(np.zeros(4, np.int32), steps=0)
        with pytest.raises(NotImplementedError, match="dense"):
            ServingEngine(init_params(_cfg(window=8), seed=0),
                          _cfg(window=8))
        moe = _cfg(n_experts=2)
        with pytest.raises(NotImplementedError, match="MoE"):
            ServingEngine(init_params(moe, seed=0), moe)


class TestServingCompileAndDonation:
    def test_no_recompile_across_admissions_and_rows(self):
        # Compile-count teeth (the test_decode_donation.py idiom): a
        # serving run with 9 admissions across every row of the batch,
        # all prompts inside one 16-bucket, costs exactly ONE admission
        # compile and ONE round compile — row index, prompt length, and
        # fill state are traced, never baked in. vocab=52 makes this
        # cfg unique to the test, so the jit-cache delta is exact no
        # matter which tests compiled what before it.
        cfg = _cfg(vocab=52)
        params = init_params(cfg, seed=6)
        eng = ServingEngine(params, cfg, batch=3, round_steps=4)
        rng = np.random.default_rng(1)
        admit0 = prefill_into_row._cache_size()
        round0 = _decode_round._cache_size()
        workload = [(rng.integers(0, cfg.vocab, int(s)), int(st))
                    for s, st in zip(rng.integers(4, 16, 9),
                                     rng.integers(2, 12, 9))]
        _run_workload(eng, workload, waves=3)
        assert eng.stats.n_completed == 9
        assert prefill_into_row._cache_size() == admit0 + 1
        assert _decode_round._cache_size() == round0 + 1
        # A second engine on the same shapes adds nothing either.
        eng2 = ServingEngine(params, cfg, batch=3, round_steps=4)
        eng2.submit(rng.integers(0, cfg.vocab, 8), 4)
        eng2.run()
        assert prefill_into_row._cache_size() == admit0 + 1
        assert _decode_round._cache_size() == round0 + 1

    def test_cache_and_buffer_stay_in_place_across_swaps(self):
        # Donation aliasing across the whole serving lifetime: after
        # warmup, every admission and every round updates the SAME
        # device buffers — no per-admission cache rebuild, no round
        # copy. (unsafe_buffer_pointer equality, as in
        # test_decode_donation.py.)
        cfg = _cfg()
        params = init_params(cfg, seed=8)
        eng = ServingEngine(params, cfg, batch=2, round_steps=4)
        rng = np.random.default_rng(3)
        # Warmup: first admission + first round allocate the aliased
        # storage the engine then lives in.
        eng.submit(rng.integers(0, cfg.vocab, 8), 3)
        eng.run()

        def pointers():
            ptrs = [eng._buf.unsafe_buffer_pointer()]
            for layer in eng._cache:
                ptrs += [v.unsafe_buffer_pointer()
                         for v in layer.values()]
            return ptrs

        before = pointers()
        for _ in range(4):
            eng.submit(rng.integers(0, cfg.vocab, 8), 5)
        eng.run()
        assert pointers() == before
