"""Matrix-ops-as-a-service tests (marlin_tpu/serving/jobs.py +
``POST /v1/matrix``; docs/matrix_service.md).

The ISSUE-20 acceptance claims, each pinned mechanically:

* TYPED ADMISSION — no job reaches the driver unpriced: every
  malformed body is a :class:`MatrixJobError` with a stable ``code``
  and structured ``detail``, mapped to an HTTP 400 body the client
  surfaces as ``error_code``.
* BYTE-TRANSPARENCY — the npz payload fetched over a real socket
  decodes to arrays BYTE-identical to the in-process
  ``matrix_compute`` call of the same body, across
  f32 / f64 / bfloat16 / int8, blocking and SSE alike (the
  quantum-sliced executors ARE the library loops run in slices).
* QUANTUM ACCOUNTING — admission prices the same quantum count the
  executor later reports (``executor_quanta`` vs ``n_quanta``), and
  engine round events carry the interleaved ``matrix_quanta``.
* CHAOS — a deterministic ``matrix_quantum`` crash mid-job replays the
  job from its seed after the supervisor restart and produces the same
  bytes; repeated crashes quarantine the job as a typed
  ``PoisonedRequest``.
* RETRY IDEMPOTENCY — a matrix job that streamed progress events is
  never silently resent by the client retry policy (the exact rule
  token streams follow).
* FLEET JOB CLASS — ``FleetConfig.matrix_group`` carves the dedicated
  tail group, ``replica_argv`` arms exactly those replicas, and the
  group rides ``RouteDecision.group`` so failover stays inside it.

The bench smoke at the bottom runs the real ``bench.py --config
matrix_service`` subprocess and holds its artifact to the committed SLO
baseline's ``metrics_matrix`` block.
"""

import importlib.util
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from marlin_tpu.models import TransformerConfig, init_params
from marlin_tpu.obs.metrics import MetricsRegistry
from marlin_tpu.obs.runlog import RunLog
from marlin_tpu.serving import (EngineFrontend, MatrixJobError,
                                MatrixService, PoisonedRequest,
                                ServingEngine, faults, serve)
from marlin_tpu.serving.jobs import (build_executor, decode_result,
                                     encode_result, executor_quanta,
                                     generate_inputs, matrix_compute,
                                     validate_job)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _cfg(**kw):
    base = dict(vocab=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
                max_len=32)
    base.update(kw)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return init_params(cfg, seed=0), cfg


@pytest.fixture(scope="module")
def mx_server(model):
    params, cfg = model
    srv = serve(params, cfg, port=0, batch=2, round_steps=4,
                max_pending=8, seed=0, matrix=True).start_background()
    yield srv
    try:
        srv.close_now()
    except OSError:
        pass


@pytest.fixture(scope="module")
def client_mod():
    return _load_tool("serving_client")


def _assert_bytes_equal(arrays, ref):
    assert sorted(arrays) == sorted(ref)
    for k in ref:
        got, want = np.asarray(arrays[k]), np.asarray(ref[k])
        assert got.dtype == want.dtype, (k, got.dtype, want.dtype)
        assert got.shape == want.shape, (k, got.shape, want.shape)
        assert got.tobytes() == want.tobytes(), k


class TestTypedValidation:
    """No job reaches the driver unpriced: every malformed body is a
    typed rejection with a stable code + structured detail."""

    @pytest.mark.parametrize("body,code", [
        ({"op": "qr", "shapes": [4, 4], "dtype": "float32",
          "seed": 1}, "unknown_op"),
        ({"op": "gemm", "shapes": [4, "x", 4], "dtype": "float32",
          "seed": 1}, "bad_shape"),
        ({"op": "gemm", "shapes": [4, 4], "dtype": "float32",
          "seed": 1}, "bad_shape"),           # gemm arity is 3 (m,k,n)
        ({"op": "lu", "shapes": [0], "dtype": "float32",
          "seed": 1}, "bad_shape"),
        ({"op": "gemm", "shapes": [1 << 20, 4, 4], "dtype": "float32",
          "seed": 1}, "shape_overflow"),
        ({"op": "lu", "shapes": [8], "dtype": "int8",
          "seed": 1}, "bad_dtype"),           # int8 is gemm-only
        ({"op": "gemm", "shapes": [4, 4, 4], "dtype": "float32",
          "seed": "not-an-int"}, "bad_inputs"),
        ({"op": "gemm", "shapes": [4, 4, 4], "dtype": "float32",
          "seed": 1, "payload": {}}, "bad_inputs"),   # both
        ({"op": "svd", "shapes": [8, 8], "dtype": "float32", "seed": 1,
          "k": 99}, "bad_knob"),
    ])
    def test_typed_rejections(self, body, code):
        with pytest.raises(MatrixJobError) as ei:
            validate_job(body)
        assert ei.value.code == code
        assert isinstance(ei.value.detail, dict)

    def test_payload_mismatch_is_typed(self):
        spec = validate_job({"op": "gemm", "shapes": [4, 3, 2],
                             "dtype": "float32", "seed": 0})
        ok = matrix_compute({"op": "gemm", "shapes": [4, 3, 2],
                             "dtype": "float32", "seed": 0})
        assert spec.op == "gemm" and "c" in ok
        with pytest.raises(MatrixJobError) as ei:
            validate_job({"op": "gemm", "shapes": [4, 3, 2],
                          "dtype": "float32",
                          "payload": {"a": [[1.0, 2.0]],
                                      "b": [[1.0], [2.0]]}})
        assert ei.value.code == "payload_mismatch"

    def test_service_counts_rejections(self):
        reg = MetricsRegistry()
        mx = MatrixService(metrics=reg)
        with pytest.raises(MatrixJobError):
            mx.validate({"op": "qr", "shapes": [4, 4],
                         "dtype": "float32", "seed": 1})
        snap = reg.snapshot()
        assert snap["counters"][
            "serving_matrix_jobs_rejected_total"] == 1


class TestExecutorContracts:
    @pytest.mark.parametrize("body", [
        {"op": "gemm", "shapes": [70, 16, 8], "dtype": "float32",
         "seed": 2, "panel": 32},
        {"op": "lu", "shapes": [40], "dtype": "float32", "seed": 2,
         "base": 16},
        {"op": "spmm", "shapes": [64, 32, 8], "dtype": "float32",
         "seed": 2, "nnz_chunk": 17},
        {"op": "cholesky", "shapes": [12], "dtype": "float32",
         "seed": 2},
        {"op": "svd", "shapes": [16, 12], "dtype": "float32",
         "seed": 2, "k": 3},
        {"op": "inverse", "shapes": [10], "dtype": "float32",
         "seed": 2},
    ])
    def test_pricing_and_executor_agree_on_quanta(self, body):
        """Admission prices the SAME quantum count the executor later
        reports — the invariant that keeps round budgets honest."""
        spec = validate_job(dict(body))
        ex = build_executor(spec)
        assert executor_quanta(spec) == ex.n_quanta
        steps = 0
        while not ex.done:
            ex.step()
            steps += 1
        assert steps == ex.n_quanta

    def test_lu_executor_matches_library_bytes(self):
        """The quantum-sliced LU IS ``lu_factor_array(mode="dist")``
        paused between panels — byte-identical output."""
        import jax

        from marlin_tpu.linalg.lu import lu_factor_array

        body = {"op": "lu", "shapes": [48], "dtype": "float32",
                "seed": 5, "base": 16}
        out = matrix_compute(dict(body))
        a = generate_inputs(validate_job(dict(body)))["a"]
        packed, perm = lu_factor_array(a, mode="dist", base_size=16)
        assert np.asarray(out["lu"]).tobytes() == \
            np.asarray(jax.device_get(packed)).tobytes()
        assert np.asarray(out["perm"]).tolist() == \
            np.asarray(perm).tolist()

    def test_npz_roundtrip_preserves_nonnative_dtypes(self):
        import ml_dtypes

        arrays = {
            "x": np.arange(6, dtype=np.float32).reshape(2, 3)
            .astype(ml_dtypes.bfloat16),
            "q": np.array([[-127, 3], [5, 127]], dtype=np.int8),
        }
        payload = encode_result(dict(arrays), {"op": "t"})
        back, meta = decode_result(payload)
        assert meta["op"] == "t"
        _assert_bytes_equal(back, arrays)


class TestHTTPRoundtrips:
    """f32/f64/bf16/int8 over a real socket, value-exact against the
    in-process call — the service's byte-transparency contract."""

    @pytest.mark.parametrize("body", [
        {"op": "gemm", "shapes": [24, 16, 12], "dtype": "float32",
         "seed": 7},
        {"op": "gemm", "shapes": [24, 16, 12], "dtype": "float64",
         "seed": 7},
        {"op": "gemm", "shapes": [24, 16, 12], "dtype": "bfloat16",
         "seed": 7},
        {"op": "gemm", "shapes": [24, 16, 12], "dtype": "int8",
         "seed": 7},
        {"op": "lu", "shapes": [32], "dtype": "float32", "seed": 8},
        {"op": "cholesky", "shapes": [16], "dtype": "float64",
         "seed": 9},
        {"op": "spmm", "shapes": [32, 32, 8], "dtype": "float32",
         "seed": 10},
        {"op": "svd", "shapes": [16, 12], "dtype": "float32",
         "seed": 11, "k": 3},
        {"op": "inverse", "shapes": [12], "dtype": "float32",
         "seed": 12},
    ])
    def test_blocking_roundtrip_value_exact(self, mx_server,
                                            client_mod, body):
        c = client_mod.ServingClient(port=mx_server.port)
        res = c.matrix(**dict(body))
        assert res["code"] == 200, res
        ref = matrix_compute(dict(body))
        _assert_bytes_equal(res["arrays"], ref)
        # The npz payload is self-describing: decoding the raw wire
        # bytes reproduces the same arrays AND the header meta.
        arrays, meta = decode_result(res["payload_bytes"])
        _assert_bytes_equal(arrays, ref)
        assert meta == res["meta"]
        assert meta["op"] == body["op"] and meta["status"] == "done"
        assert meta["budget_rel_err"] is None or \
            meta["budget_rel_err"] >= 0

    def test_stream_matches_blocking_bytes(self, mx_server,
                                           client_mod):
        body = {"op": "gemm", "shapes": [48, 16, 8], "dtype": "float32",
                "seed": 21}
        c = client_mod.ServingClient(port=mx_server.port)
        blocking = c.matrix(**dict(body))
        streamed = c.matrix_stream(**dict(body))
        assert streamed["code"] == 200, streamed
        # Same bytes either way (meta carries per-job ids/timings, so
        # compare the arrays the payloads decode to).
        _assert_bytes_equal(streamed["arrays"], blocking["arrays"])
        phases = [e.get("phase") for e in streamed["events"]]
        assert "queued" in phases and "execute" in phases
        # Progress is monotone over quanta.
        progress = [e["progress"] for e in streamed["events"]
                    if "progress" in e]
        assert progress == sorted(progress)

    def test_http_typed_400_and_bad_json(self, mx_server, client_mod):
        c = client_mod.ServingClient(port=mx_server.port)
        res = c.matrix("qr", [4, 4], seed=1)
        assert res["code"] == 400
        assert res["error_code"] == "unknown_op"
        assert "detail" in res
        # Malformed JSON never reaches validation: typed bad_json.
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", mx_server.port,
                                          timeout=30)
        try:
            conn.request("POST", "/v1/matrix", b"{nope",
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            err = json.loads(resp.read())
            assert resp.status == 400
            assert err["code"] == "bad_json"
        finally:
            conn.close()

    def test_matrixless_server_404s(self, model, client_mod):
        params, cfg = model
        srv = serve(params, cfg, port=0, batch=2, round_steps=4,
                    seed=0).start_background()
        try:
            c = client_mod.ServingClient(port=srv.port)
            res = c.matrix("gemm", [4, 4, 4], seed=1)
            assert res["code"] == 404
            assert "--matrix" in res["error"]
        finally:
            srv.begin_drain(30.0)

    def test_llm_traffic_interleaves_and_rounds_carry_quanta(
            self, mx_server, client_mod):
        """Mixed traffic on one driver thread: an LLM stream and a
        matrix job in flight together, and the engine's round events
        narrate the interleave via ``matrix_quanta``."""
        c = client_mod.ServingClient(port=mx_server.port)
        rng = np.random.default_rng(0)
        prompt = rng.integers(0, 64, 8).astype(np.int32)
        import threading

        mx_res = {}

        def job():
            mx_res.update(c.matrix("gemm", [64, 32, 16], seed=33))

        t = threading.Thread(target=job)
        t.start()
        llm = c.stream(prompt, 8)
        t.join(60.0)
        assert llm["code"] == 200 and len(llm["tokens"]) == 8
        assert mx_res["code"] == 200
        ref = matrix_compute({"op": "gemm", "shapes": [64, 32, 16],
                              "dtype": "float32", "seed": 33})
        _assert_bytes_equal(mx_res["arrays"], ref)
        code, dbg_raw, _ = c._get("/debug/engine")
        assert code == 200
        dbg = json.loads(dbg_raw)
        assert dbg["matrix"]["jobs_done"] >= 1


class TestChaosReplay:
    def _frontend(self, model, runlog=None, poison_after=2):
        params, cfg = model
        reg = MetricsRegistry()
        eng = ServingEngine(params, cfg, batch=2, round_steps=4,
                            metrics_registry=reg, seed=0,
                            runlog=runlog)
        mx = MatrixService(metrics=reg, runlog=runlog,
                           poison_after=poison_after)
        return EngineFrontend(eng, matrix=mx).start(), reg

    def test_crash_mid_job_replays_bitexact_from_seed(self, model,
                                                      tmp_path):
        """The crash boundary: a matrix_quantum fault kills the driver
        mid-job; the supervisor restarts the engine, the service
        replays the job FROM ITS SEED, and the delivered bytes equal
        an undisturbed run."""
        body = {"op": "lu", "shapes": [48], "dtype": "float32",
                "seed": 13, "base": 16}
        ref = matrix_compute(dict(body))
        runlog = RunLog(path=str(tmp_path / "chaos.jsonl"))
        plan = faults.install(faults.FaultPlan())
        crash = plan.add(site="matrix_quantum", action="raise")
        try:
            fe, reg = self._frontend(model, runlog=runlog)
            h = fe.submit_matrix(validate_job(dict(body)))
            payload, meta = h.result(timeout=120.0)
            assert crash.fires == 1
            assert fe.restarts == 1
            assert meta["status"] == "done"
            assert meta["crash_count"] == 1
            arrays, _ = decode_result(payload)
            _assert_bytes_equal(arrays, ref)
            # And the payload equals a never-crashed service's bytes
            # except the crash_count it honestly reports.
            assert fe.drain(30.0)
        finally:
            faults.reset()
        events = [json.loads(l) for l in
                  open(tmp_path / "chaos.jsonl")]
        kinds = [e["kind"] for e in events]
        assert "job_replay" in kinds
        replay = next(e for e in events if e["kind"] == "job_replay")
        assert replay["crash_count"] == 1

    def test_repeated_crashes_quarantine_as_poisoned(self, model):
        body = {"op": "gemm", "shapes": [32, 16, 8],
                "dtype": "float32", "seed": 14}
        plan = faults.install(faults.FaultPlan())
        plan.add(site="matrix_quantum", action="raise", max_fires=5)
        try:
            fe, reg = self._frontend(model, poison_after=2)
            h = fe.submit_matrix(validate_job(dict(body)))
            with pytest.raises(PoisonedRequest):
                h.result(timeout=120.0)
            snap = reg.snapshot()
            assert snap["counters"][
                "serving_matrix_jobs_poisoned_total"] == 1
            assert fe.drain(30.0)
        finally:
            faults.reset()

    def test_poisoned_maps_to_500_over_http(self, model, client_mod):
        params, cfg = model
        plan = faults.install(faults.FaultPlan())
        plan.add(site="matrix_quantum", action="raise", max_fires=5)
        try:
            srv = serve(params, cfg, port=0, batch=2, round_steps=4,
                        seed=0, matrix=True).start_background()
            try:
                c = client_mod.ServingClient(port=srv.port)
                res = c.matrix("gemm", [16, 8, 8], seed=15)
                assert res["code"] == 500
                assert "crash" in json.dumps(res).lower() or \
                    res.get("error")
            finally:
                srv.begin_drain(30.0)
        finally:
            faults.reset()


class TestClientRetrySemantics:
    def test_streamed_progress_is_never_silently_resent(self,
                                                        client_mod):
        """The idempotency guard's matrix arm: a retryable result that
        already delivered progress EVENTS stops the retry loop exactly
        like delivered tokens do."""
        sc = client_mod
        policy = sc.RetryPolicy(max_attempts=4, base_delay_s=0.0)
        calls = []

        def partial_stream():
            calls.append(1)
            return {"code": 503, "retry_after": None,
                    "events": [{"phase": "execute", "quantum": 1}],
                    "stream_error": "died mid-progress"}

        res = sc.call_with_retry(partial_stream, policy, key="k",
                                 sleep=lambda s: None)
        assert res["attempts"] == 1 and len(calls) == 1

        def clean_503():
            calls.append(1)
            return {"code": 503, "retry_after": None}

        calls.clear()
        res = sc.call_with_retry(clean_503, policy, key="k",
                                 sleep=lambda s: None)
        assert res["attempts"] == 4 and len(calls) == 4


class TestFleetJobClass:
    def test_matrix_group_and_replica_argv(self):
        from marlin_tpu.fleet.config import FleetConfig

        off = FleetConfig(n_replicas=3)
        assert off.matrix_group() == ()
        both = FleetConfig(n_replicas=3, matrix=True)
        assert both.matrix_group() == (0, 1, 2)
        tail = FleetConfig(n_replicas=4, matrix=True,
                           matrix_replicas=2)
        assert tail.matrix_group() == (2, 3)
        assert "--matrix" not in tail.replica_argv(0, 0)
        assert "--matrix" in tail.replica_argv(3, 0)
        with pytest.raises(ValueError):
            FleetConfig(n_replicas=2, matrix_replicas=1)  # no matrix
        with pytest.raises(ValueError):
            FleetConfig(n_replicas=2, matrix=True, matrix_replicas=3)

    def test_route_matrix_stays_in_group(self):
        from marlin_tpu.fleet.config import FleetConfig
        from marlin_tpu.fleet.router import PrefixAffinityRouter

        class _Stub:
            healthy = True

        cfg = FleetConfig(n_replicas=4, matrix=True, matrix_replicas=2)
        router = PrefixAffinityRouter([_Stub() for _ in range(4)],
                                      cfg, MetricsRegistry())
        seen = set()
        decisions = []
        for _ in range(6):
            d = router.route_matrix()
            decisions.append(d)
            seen.add(d.replica_index)
            assert d.group == (2, 3)
        assert seen == {2, 3}  # least-outstanding spreads the group
        # Failover candidates honor the group constraint.
        nxt = router.next_candidate(tried={2}, group=(2, 3))
        assert nxt == 3
        assert router.next_candidate(tried={2, 3}, group=(2, 3)) is None
        for d in decisions:
            router.release(d)


# -- the bench artifact + SLO gate ------------------------------------


class TestMatrixSloSmoke:
    def test_bench_matrix_line_and_slo_gate(self, tmp_path):
        """`bench.py --config matrix_service` end to end with tiny
        knobs: mixed LLM+matrix traffic, byte-exactness, zero
        steady-state recompiles, the LLM SLO green, and the pricing
        bar — then tools/slo_check.py --metrics-key metrics_matrix
        against the committed baseline (the tier-1 SLO gate)."""
        env = dict(
            os.environ, BENCH_FORCE_CPU="1", BENCH_RETRIES="1",
            BENCH_MX_D="32", BENCH_MX_L="2", BENCH_MX_REQS="6",
            BENCH_MX_STEPS="6", BENCH_MX_CONC="3", BENCH_MX_ROUND="4",
            BENCH_MX_VOCAB="64")
        r = subprocess.run(
            [sys.executable, "bench.py", "--config", "matrix_service"],
            capture_output=True, text=True, timeout=420, env=env,
            cwd=_REPO)
        assert r.returncode == 0, r.stderr[-800:]
        lines = [json.loads(l) for l in r.stdout.strip().splitlines()]
        (line,) = [d for d in lines
                   if d["metric"] == "serving_matrix_service"]
        assert line["bitexact"] == 1
        assert line["llm_slo_ok"] == 1
        assert line["recompiles_after_warmup"] == 0
        assert line["matrix_jobs_exact"] == line["matrix_jobs_checked"]
        assert line["budget_rel_err_p50"] is not None
        assert line["drain_ok"] is True
        assert line["metrics"]["histograms"][
            "serving_matrix_job_seconds"]["count"] > 0
        artifact = tmp_path / "matrix_artifact.jsonl"
        artifact.write_text(r.stdout)
        slo = subprocess.run(
            [sys.executable, "tools/slo_check.py", str(artifact),
             "--metrics-key", "metrics_matrix"],
            capture_output=True, text=True, timeout=60, cwd=_REPO)
        assert slo.returncode == 0, slo.stdout + slo.stderr
        assert "SLO OK" in slo.stdout
