"""Race/nondeterminism detection subsystem (utils.doctor).

The reference has no race detection (SURVEY.md §5); these tests pin down the
TPU-native hazard classes the subsystem covers: kernel nondeterminism,
implicit transfers, NaN escapes, donated-buffer reuse.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marlin_tpu.utils import doctor


class TestDeterminism:
    def test_deterministic_jit_passes(self):
        f = jax.jit(lambda x: jnp.sin(x) @ jnp.cos(x.T))
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 16))
        rep = doctor.check_determinism(f, x, runs=3)
        assert rep.deterministic and not rep.mismatches

    def test_summa_engine_is_deterministic(self, mesh):
        from marlin_tpu.utils import random as mrand

        a = mrand.random_den_vec_matrix(32, 24, seed=1)
        b = mrand.random_den_vec_matrix(24, 16, seed=2)
        rep = doctor.check_determinism(
            lambda: a.multiply(b, mode="summa").to_numpy(), runs=3
        )
        assert rep.deterministic

    def test_nondeterministic_fn_flagged(self):
        state = {"n": 0}

        def flaky(x):
            state["n"] += 1
            return x + state["n"]

        rep = doctor.check_determinism(flaky, jnp.ones((4,)), runs=2)
        assert not rep.deterministic
        assert rep.max_abs_diff > 0

    def test_pytree_mismatch_paths_named(self):
        state = {"n": 0}

        def flaky(x):
            state["n"] += 1
            return {"stable": x, "drifting": x * state["n"]}

        rep = doctor.check_determinism(flaky, jnp.ones((4,)), runs=2)
        assert any("drifting" in p for p in rep.mismatches)
        assert not any("stable" in p for p in rep.mismatches)

    def test_tolerance_mode(self):
        state = {"n": 0}

        def jitter(x):
            state["n"] += 1
            return x + 1e-9 * state["n"]

        assert doctor.check_determinism(
            jitter, jnp.ones((4,)), runs=2, bitwise=False, atol=1e-6
        )
        assert not doctor.check_determinism(jitter, jnp.ones((4,)), runs=2)

    def test_runs_validation(self):
        with pytest.raises(ValueError, match="runs"):
            doctor.check_determinism(lambda: 0, runs=1)


class TestTransferGuard:
    def test_guard_level_scoped(self):
        # CPU-backend transfers are zero-copy and never trip the guard, so
        # assert the level is plumbed through jax's config for the scope.
        before = jax.config.jax_transfer_guard
        with doctor.transfer_guard("disallow"):
            assert jax.config.jax_transfer_guard == "disallow"
        assert jax.config.jax_transfer_guard == before

    def test_blocks_implicit_host_transfer_on_accelerator(self):
        if jax.devices()[0].platform == "cpu":
            pytest.skip("host<->CPU-device copies are zero-copy exempt")
        x = jnp.arange(8.0)
        with pytest.raises(Exception, match="[Tt]ransfer"):
            with doctor.transfer_guard("disallow"):
                np.asarray(x) + 1  # implicit device->host

    def test_allows_inside_allow_level(self):
        x = jnp.arange(8.0)
        with doctor.transfer_guard("allow"):
            assert float(np.asarray(x).sum()) == 28.0


class TestFinite:
    def test_passes_finite_tree(self):
        tree = {"a": jnp.ones((3,)), "b": np.zeros((2, 2))}
        assert doctor.check_finite(tree) is tree

    def test_names_bad_leaf(self):
        tree = {"good": jnp.ones((2,)), "bad": jnp.array([1.0, np.inf])}
        with pytest.raises(doctor.NonFiniteError) as e:
            doctor.check_finite(tree, name="grads")
        assert any("bad" in p for p in e.value.paths)
        assert not any("good" in p for p in e.value.paths)

    def test_int_leaves_ignored(self):
        doctor.check_finite({"i": jnp.arange(4)})


class TestDonation:
    def test_safe_fn(self):
        f = jax.jit(lambda x: x * 2)
        assert doctor.check_donation_safe(f, jnp.ones((4,)))

    def test_donated_buffer_flagged(self):
        f = jax.jit(lambda x: x * 2, donate_argnums=0)
        x = jnp.ones((256,))
        assert not doctor.check_donation_safe(f, x)


class TestAudit:
    def test_clean_function(self):
        f = jax.jit(lambda x: x @ x.T)
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 8))
        rep = doctor.audit(f, x)
        assert rep["deterministic"] and rep["donation_safe"] and rep["finite"]

    def test_nan_producer(self):
        f = lambda x: jnp.log(x - 10.0)  # negative -> NaN
        rep = doctor.audit(f, jnp.ones((4,)))
        assert not rep["finite"] and rep["nonfinite_leaves"]

    def test_audit_with_donated_inputs(self):
        # check_determinism host-fetches operands, so a donate_argnums fn
        # can't invalidate them between runs; audit still flags the donation.
        f = jax.jit(lambda x: x * 2, donate_argnums=0)
        rep = doctor.audit(f, jnp.ones((256,)))
        assert rep["deterministic"] and not rep["donation_safe"]
