"""serving/sched.py — the SLO-aware multi-tenant scheduler (ISSUE 17,
docs/serving.md §8).

Four layers, each pinned:

* POLICY UNITS (no engine): ClassSpec/Scheduler validation, EDF
  ordering within a class, rank precedence across classes, quota
  accounting that stays work-conserving, expiry at pop time, the
  preemption candidate/victim/cost-gate policy, and the metrics
  recorders — all on the pure policy object.
* PREEMPTION MECHANISM (real engines): an interactive arrival freezes
  a decoding batch row at a round boundary, spills it through the host
  tier, resumes it, and every request's output is byte-identical to a
  FIFO engine that never preempted (plain in tier-1; rope+GQA / int8 /
  speculative-greedy variants under -m slow — the bench's bit-exact
  matrix runs all four in the SLO smoke below). Clean aborts (cost
  gate, host budget) leave outputs untouched; a frozen request dropped
  for deadline releases its pinned host row (the reservation-leak
  regression); the runlog/metrics/debug surfaces narrate every freeze.
* CHAOS: a deterministic ``preempt_spill`` crash under the supervised
  frontend replays from scratch to the same bytes (the fault fires
  after the victim is chosen and BEFORE its pages move, so the crashed
  incarnation loses nothing it can't recompute).
* CI FORM: ``bench.py --config tenants`` through tools/slo_check.py
  ``--metrics-key metrics_tenants`` (chat-tail improvement >= 3x,
  batch cost <= 20%, zero steady-state recompiles in both arms), plus
  the server/fleet argv plumbing (``--sched``, ``/debug/sched``,
  tenant/sched_class POST fields and their 400 mapping).
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from marlin_tpu.models import TransformerConfig, init_params
from marlin_tpu.obs.metrics import MetricsRegistry
from marlin_tpu.obs.runlog import RunLog
from marlin_tpu.serving import (DEFAULT_CLASSES, ClassSpec,
                                EngineFrontend, Scheduler, ServingEngine,
                                faults)
from marlin_tpu.serving.queue import Request
from marlin_tpu.utils import cost_model as cm

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(**kw):
    base = dict(vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                max_len=96)
    base.update(kw)
    return TransformerConfig(**base)


def _req(rid, cls="", submit=0.0, deadline_time=None,
         deadline_rounds=None):
    return Request(request_id=rid,
                   prompt=np.zeros(4, np.int32), steps=4,
                   deadline_time=deadline_time,
                   deadline_rounds=deadline_rounds,
                   submit_time=submit, sched_class=cls)


class TestClassSpec:
    def test_validation(self):
        with pytest.raises(ValueError, match="identifier"):
            ClassSpec("", rank=0)
        with pytest.raises(ValueError, match="identifier"):
            ClassSpec("no-dashes", rank=0)
        with pytest.raises(ValueError, match="quota"):
            ClassSpec("a", rank=0, quota=0)
        with pytest.raises(ValueError, match="slo_s"):
            ClassSpec("a", rank=0, slo_s=0.0)

    def test_default_taxonomy(self):
        by_name = {c.name: c for c in DEFAULT_CLASSES}
        assert set(by_name) == {"interactive", "batch", "best_effort"}
        it = by_name["interactive"]
        assert it.rank == 0 and it.can_preempt and not it.preemptible
        assert it.slo_s == 1.0
        assert by_name["batch"].preemptible
        assert not by_name["batch"].can_preempt


class TestSchedulerPolicy:
    def test_constructor_validation(self):
        with pytest.raises(ValueError, match="at least one"):
            Scheduler(classes=())
        with pytest.raises(ValueError, match="duplicate class names"):
            Scheduler(classes=(ClassSpec("a", 0), ClassSpec("a", 1)))
        with pytest.raises(ValueError, match="ranks must be unique"):
            Scheduler(classes=(ClassSpec("a", 0), ClassSpec("b", 0)))
        with pytest.raises(ValueError, match="default_class"):
            Scheduler(default_class="gold")
        with pytest.raises(ValueError, match="max_preempts_per_round"):
            Scheduler(max_preempts_per_round=-1)

    def test_resolve_default_and_unknown(self):
        s = Scheduler()
        assert s.resolve(None).name == "interactive"  # lowest rank
        assert s.resolve("batch").name == "batch"
        with pytest.raises(ValueError, match="unknown scheduling class"):
            s.resolve("gold")

    def test_edf_orders_within_class(self):
        # batch has no SLO, so the caller deadline alone is the EDF
        # key; deadline-less requests sort last, FIFO among themselves.
        s = Scheduler()
        for r in (_req(0, "batch"), _req(1, "batch", deadline_time=50.0),
                  _req(2, "batch", deadline_time=20.0),
                  _req(3, "batch")):
            s.push(r)
        order = []
        while len(s):
            req, expired = s.pop(0, now=0.0)
            assert expired == []
            order.append(req.request_id)
        assert order == [2, 1, 0, 3]

    def test_class_slo_caps_the_effective_deadline(self):
        # interactive's slo_s=1.0 beats a lazy caller deadline: the
        # submit+slo target is what EDF sorts by.
        s = Scheduler()
        early = _req(0, "interactive", submit=5.0)       # target 6.0
        capped = _req(1, "interactive", submit=0.0,
                      deadline_time=100.0)               # target 1.0
        assert s.effective_deadline(early) == 6.0
        assert s.effective_deadline(capped) == 1.0

    def test_rank_beats_deadline_across_classes(self):
        s = Scheduler()
        s.push(_req(0, "batch", deadline_time=0.5))  # urgent but rank 1
        s.push(_req(1, "interactive", submit=10.0))  # target 11.0
        req, _ = s.pop(0, now=0.0)
        assert req.request_id == 1

    def test_quota_bounds_only_under_contention(self):
        classes = (ClassSpec("gold", 0, quota=1, can_preempt=True),
                   ClassSpec("bulk", 1))
        s = Scheduler(classes=classes)
        s.push(_req(0, "gold"))
        s.push(_req(1, "bulk"))
        # gold at quota: the first pass skips it, bulk admits.
        req, _ = s.pop(0, now=0.0, occupancy={"gold": 1})
        assert req.request_id == 1
        # Nothing else admissible: work conservation hands gold out
        # anyway rather than parking an idle row (second pass).
        req, _ = s.pop(0, now=0.0, occupancy={"gold": 1})
        assert req.request_id == 0
        # Under quota, gold admits in rank order as usual.
        s.push(_req(2, "gold"))
        s.push(_req(3, "bulk"))
        req, _ = s.pop(0, now=0.0, occupancy={"gold": 0})
        assert req.request_id == 2

    def test_pop_drops_expired_with_timeout_status(self):
        # Request 0 expires by wall clock, request 2 by round budget
        # (its future deadline_time keeps it AHEAD of the deadline-less
        # request 1 in the EDF heap, so the scan reaches it).
        s = Scheduler()
        s.push(_req(0, "batch", deadline_time=1.0))
        s.push(_req(1, "batch"))
        s.push(_req(2, "batch", deadline_time=10.0, deadline_rounds=3))
        req, expired = s.pop(round_idx=5, now=2.0)
        assert req.request_id == 1
        assert sorted(r.request_id for r in expired) == [0, 2]
        assert all(r.status == "timeout" for r in expired)
        assert all(r.finish_round == 5 for r in expired)
        assert len(s) == 0

    def test_push_assigns_sequence_once(self):
        # A re-push (page-pressure probe, preemption requeue) keeps its
        # original FIFO position among equal deadlines.
        s = Scheduler()
        first = _req(0, "batch")
        s.push(first)
        s.push(_req(1, "batch"))
        popped, _ = s.pop(0, now=0.0)
        assert popped is first and first.sched_seq == 0
        s.push(first)  # requeue: seq survives, so it pops FIRST again
        assert first.sched_seq == 0
        again, _ = s.pop(0, now=0.0)
        assert again is first

    def test_preempt_candidate_rank_order(self):
        s = Scheduler()
        assert s.preempt_candidate(now=0.0) is None
        s.push(_req(0, "batch"))
        assert s.preempt_candidate(now=0.0) is None  # cannot preempt
        it = _req(1, "interactive")
        s.push(it)
        assert s.preempt_candidate(now=0.0) is it
        # Peeking must not pop: the head stays queued.
        assert len(s) == 2

    def test_victim_order_prefers_lowest_priority_most_work(self):
        s = Scheduler()
        cands = [(_req(0, "batch"), 30), (_req(1, "batch"), 90),
                 (_req(2, "best_effort"), 5),
                 (_req(3, "interactive"), 99)]
        order = s.victim_order(cands, requester_rank=0)
        # interactive is non-preemptible and not strictly lower
        # priority; best_effort (lowest priority) leads despite the
        # least remaining work; then batch, most-remaining first.
        assert [r.request_id for r, _ in order] == [2, 1, 0]
        # A batch-rank requester may only displace best_effort.
        order = s.victim_order(cands, requester_rank=1)
        assert [r.request_id for r, _ in order] == [2]
        # Equal class and remaining: larger id (newest) first, so the
        # longest-running victim is spared deterministically.
        tie = s.victim_order([(_req(7, "batch"), 30),
                              (_req(4, "batch"), 30)], requester_rank=0)
        assert [r.request_id for r, _ in tie] == [7, 4]

    def test_spawn_successor_carries_policy_not_heaps(self):
        classes = (ClassSpec("gold", 0, quota=2, can_preempt=True),
                   ClassSpec("bulk", 3))
        s = Scheduler(classes=classes, default_class="bulk",
                      preempt_margin=2.5, max_preempts_per_round=4)
        s.push(_req(0, "gold"))
        succ = s.spawn_successor()
        assert len(succ) == 0  # fresh heaps: no double-enqueue
        assert succ.default_class == "bulk"
        assert succ.preempt_margin == 2.5
        assert succ.max_preempts_per_round == 4
        assert [c.name for c in succ.by_rank] == ["gold", "bulk"]
        assert len(s) == 1  # the crashed heap is untouched

    def test_summary_and_queued_by_class(self):
        s = Scheduler()
        s.push(_req(0, "batch"))
        s.push(_req(1, "batch"))
        assert s.queued_by_class() == {"interactive": 0, "batch": 2,
                                       "best_effort": 0}
        summ = s.summary()
        assert summ["default_class"] == "interactive"
        assert [c["name"] for c in summ["classes"]] == \
            ["interactive", "batch", "best_effort"]
        (batch,) = [c for c in summ["classes"] if c["name"] == "batch"]
        assert batch["queued"] == 2 and batch["preemptible"] is True

    def test_metrics_recorders(self):
        reg = MetricsRegistry()
        s = Scheduler(registry=reg)
        s.note_admitted(_req(0, "interactive"), queue_wait_s=0.2)
        s.note_admitted(_req(1, "interactive"), queue_wait_s=5.0)
        hist = reg.histogram("serving_sched_queue_wait_seconds",
                             cls="interactive").summary()
        assert hist["count"] == 2
        # Only the 5.0 s wait missed the 1.0 s SLO; a timeout drop is
        # always a miss for an SLO'd class and never for a bare one.
        s.note_timeout(_req(2, "interactive"))
        s.note_timeout(_req(3, "batch"))
        assert reg.counter("serving_sched_slo_miss_total",
                           cls="interactive").value == 2
        s.note_preempt(_req(4, "batch"))
        s.note_resume(_req(4, "batch"))
        s.note_preempt_abort("cost_gate")
        assert reg.counter("serving_sched_preemptions_total",
                           cls="batch").value == 1
        assert reg.counter("serving_sched_resumes_total",
                           cls="batch").value == 1
        assert reg.counter("serving_sched_preempt_aborts_total",
                           reason="cost_gate").value == 1
        s.push(_req(5, "batch"))
        s.mirror_queued()
        assert reg.gauge("serving_sched_class_queued",
                         cls="batch").value == 1.0


class TestPreemptCostModel:
    def test_preempt_cost_is_round_trip_restore(self):
        cfg = _cfg()
        _, one_way = cm.restore_cost(cfg, 64)
        flops, rt = cm.preempt_cost(cfg, 64)
        assert flops == 0.0 and rt == 2.0 * one_way

    def test_beneficial_monotone_in_remaining_work(self):
        cfg = _cfg()
        assert not cm.preempt_beneficial(cfg, 64, 0)
        assert not cm.preempt_beneficial(cfg, 64, -3)
        # A tiny model's decode step is weight-dominated: a handful of
        # remaining steps already outweighs moving a short row twice.
        assert cm.preempt_beneficial(cfg, 16, 1000)
        # Raising the margin flips the same freeze back to "let it
        # finish": conservatism scales, the model does not change.
        assert cm.preempt_beneficial(cfg, 64, 4096, margin=1.0)
        assert not cm.preempt_beneficial(cfg, 64, 4096, margin=1e9)

    def test_gate_disabled_by_nonpositive_margin(self):
        s = Scheduler(preempt_margin=0.0)
        assert not s.preempt_gate(_cfg(), 64, 10_000)
        s2 = Scheduler(preempt_margin=1.0)
        assert s2.preempt_gate(_cfg(), 16, 10_000)


# -- the preemption mechanism on real engines --------------------------

_VARIANTS = {
    "plain": ({}, False),
    "rope_gqa": ({"rope": True, "n_kv_heads": 1}, False),
    "int8": ({"kv_quant": "int8"}, False),
    "spec": ({}, True),
}


def _staggered_run(cfg_kw, spec, sched, *, scheduler=None, steps0=40,
                   steps1=40, deadline_rounds=None, host_kv_bytes=1 << 24,
                   **engine_kw):
    """The canonical preemption workload: two long batch-class jobs
    fill both rows, three rounds pass, an interactive request arrives
    (sched arm: preempts a victim). Returns ({rid: tokens}, statuses,
    engine-or-None debug snapshot)."""
    cfg = _cfg(**cfg_kw)
    params = init_params(cfg, seed=0)
    eng = ServingEngine(
        params, cfg, batch=2, round_steps=4, seed=7, kv_pages=24,
        host_kv_bytes=host_kv_bytes,
        spec_draft_lens=(4,) if spec else None,
        scheduler=(scheduler if scheduler is not None
                   else (Scheduler() if sched else None)), **engine_kw)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, cfg.vocab, 9).astype(np.int32)
               for _ in range(3)]
    kw = (lambda c: {"sched_class": c}) if sched else (lambda c: {})
    eng.submit(prompts[0], steps0, request_id=0,
               deadline_rounds=deadline_rounds, **kw("batch"))
    eng.submit(prompts[1], steps1, request_id=1, **kw("batch"))
    out, status = {}, {}
    for _ in range(3):
        for r in eng.step():
            out[r.request_id] = list(map(int, r.tokens))
            status[r.request_id] = r.status
    eng.submit(prompts[2], 6, request_id=2, **kw("interactive"))
    for _ in range(400):
        for r in eng.step():
            out[r.request_id] = (list(map(int, r.tokens))
                                 if r.tokens is not None else None)
            status[r.request_id] = r.status
        if len(out) == 3:
            break
    snap = eng.debug_sched() if eng.scheduler is not None else None
    host = eng.host_tier.summary() if eng.host_tier is not None else {}
    eng.close()
    return out, status, snap, host


class TestBitExactPreemption:
    # Tier-1 wall-clock budget (ROADMAP 9): plain in tier-1; the other
    # variants compile their own kernels and ride under -m slow (the
    # SLO smoke's bench run covers all four in-subprocess regardless).
    @pytest.mark.parametrize("name", ["plain"] + [
        pytest.param(v, marks=pytest.mark.slow)
        for v in ("rope_gqa", "int8", "spec")])
    def test_preempted_equals_uninterrupted(self, name):
        cfg_kw, spec = _VARIANTS[name]
        on, st_on, snap, host = _staggered_run(cfg_kw, spec, sched=True)
        off, st_off, _, _ = _staggered_run(cfg_kw, spec, sched=False)
        assert on == off, f"preemption moved tokens ({name})"
        assert st_on == {0: "done", 1: "done", 2: "done"}
        assert snap["preempts"] >= 1 and snap["resumes"] >= 1, \
            f"variant {name} never exercised preemption: {snap}"
        # Every pinned row drained: freeze/thaw accounting is closed.
        assert host["host_rows"] == 0
        assert host["host_row_bytes"] == 0

    def test_cost_gate_abort_is_clean(self):
        # preempt_margin <= 0 disables the gate: the interactive
        # request WAITS (no freeze), outputs still match FIFO, and the
        # abort is recorded with its reason.
        reg = MetricsRegistry()
        sched = Scheduler(preempt_margin=0.0, registry=reg)
        on, _, snap, _ = _staggered_run({}, False, sched=True,
                                        scheduler=sched,
                                        metrics_registry=reg)
        off, _, _, _ = _staggered_run({}, False, sched=False)
        assert on == off
        assert snap["preempts"] == 0 and snap["resumes"] == 0
        assert reg.counter("serving_sched_preempt_aborts_total",
                           reason="cost_gate").value >= 1

    def test_host_budget_refusal_aborts_preemption(self):
        # A host budget too small for one frozen row: spill_row
        # refuses, the victim keeps decoding, outputs match FIFO.
        reg = MetricsRegistry()
        sched = Scheduler(registry=reg)
        on, _, snap, host = _staggered_run({}, False, sched=True,
                                           scheduler=sched,
                                           host_kv_bytes=4096,
                                           metrics_registry=reg)
        off, _, _, _ = _staggered_run({}, False, sched=False)
        assert on == off
        assert snap["preempts"] == 0
        assert host["host_rows"] == 0 and host["host_row_bytes"] == 0
        assert reg.counter("serving_sched_preempt_aborts_total",
                           reason="host_budget").value >= 1

    def test_frozen_request_dropped_for_deadline_releases_row(self):
        # The reservation-leak regression (queue.on_expire ->
        # engine._release_expired -> host_tier.drop_row): request 0 is
        # frozen mid-decode, its round deadline passes while it waits
        # in the queue, and the drop must release the pinned host row
        # — without the hook the pinned-byte ledger leaks forever.
        out, status, snap, host = _staggered_run(
            {}, False, sched=True, steps0=50, steps1=30,
            deadline_rounds=4)
        # steps0 > steps1 makes request 0 the deterministic victim
        # (victim_order: most remaining work first).
        assert status[0] == "timeout"
        assert status[1] == "done" and status[2] == "done"
        assert snap["preempts"] >= 1
        assert snap["resumes"] == 0  # it never thawed: it expired
        assert host["host_rows"] == 0, "pinned row leaked on expiry"
        assert host["host_row_bytes"] == 0

    def test_preemption_is_observable(self, tmp_path):
        # One preempting drain, every narration surface checked: the
        # runlog's preempt/resume events and per-round deltas, the
        # sched counters, the row-spill counters, the engine ledger,
        # and the offline analyzer's preemption block (which must not
        # flag the freeze/thaw rounds as stalls).
        reg = MetricsRegistry()
        runlog = RunLog(maxlen=4096,
                        path=str(tmp_path / "runlog.jsonl"))
        sched = Scheduler(registry=reg)
        out, _, snap, _ = _staggered_run(
            {}, False, sched=True, scheduler=sched,
            metrics_registry=reg, runlog=runlog)
        assert snap["preempts"] >= 1 and snap["resumes"] >= 1
        frz = runlog.events("preempt")
        thaw = runlog.events("resume")
        assert len(frz) == snap["preempts"]
        assert len(thaw) == snap["resumes"]
        assert all(e["bytes"] > 0 and e["spill_s"] >= 0
                   and e["filled"] > 0 and e["pages"] >= 1
                   for e in frz)
        assert all(e["frozen_rounds"] >= 1 and e["restore_s"] >= 0
                   for e in thaw)
        rounds = runlog.events("round")
        assert sum(e.get("preempts", 0) for e in rounds) == \
            snap["preempts"]
        assert sum(e.get("resumes", 0) for e in rounds) == \
            snap["resumes"]
        assert reg.counter("serving_sched_preemptions_total",
                           cls="batch").value == snap["preempts"]
        assert reg.counter("serving_kv_row_spills_total").value == \
            snap["preempts"]
        assert reg.counter("serving_kv_row_restores_total").value == \
            snap["resumes"]
        assert reg.counter("serving_preempted_total").value == \
            snap["preempts"]
        assert reg.counter("serving_resumed_total").value == \
            snap["resumes"]
        assert reg.histogram("serving_sched_queue_wait_seconds",
                             cls="interactive").summary()["count"] >= 1
        # The offline analyzer narrates and does not cry stall.
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        try:
            import runlog_report as rr
        finally:
            sys.path.pop(0)
        report = rr.build_report(
            rr.load_runlog(str(tmp_path / "runlog.jsonl")))
        pre = report["rounds"]["preemption"]
        assert pre["preempts_total"] == snap["preempts"]
        assert pre["resumes_total"] == snap["resumes"]
        assert pre["frozen_rounds_max"] >= 1
        assert not [a for a in report["anomalies"]
                    if a["kind"] == "queue_stall"], report["anomalies"]

    def test_debug_sched_surfaces_frozen_rows(self):
        # Catch the scheduler mid-freeze: /debug/sched's engine half
        # must name the frozen request with its cursor and payload.
        cfg = _cfg()
        params = init_params(cfg, seed=0)
        eng = ServingEngine(params, cfg, batch=2, round_steps=4,
                            seed=7, kv_pages=24,
                            host_kv_bytes=1 << 24,
                            scheduler=Scheduler())
        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, cfg.vocab, 9).astype(np.int32)
                   for _ in range(3)]
        eng.submit(prompts[0], 40, request_id=0, sched_class="batch",
                   tenant="bulk-co")
        eng.submit(prompts[1], 40, request_id=1, sched_class="batch",
                   tenant="bulk-co")
        for _ in range(3):
            eng.step()
        eng.submit(prompts[2], 6, request_id=2,
                   sched_class="interactive", tenant="chat-co")
        seen_frozen = None
        for _ in range(50):
            eng.step()
            snap = eng.debug_sched()
            if snap["frozen"]:
                seen_frozen = snap
                break
        assert seen_frozen is not None, "never observed a frozen row"
        (fz,) = seen_frozen["frozen"]
        assert fz["sched_class"] == "batch"
        assert fz["tenant"] == "bulk-co"
        assert fz["filled"] > 0 and fz["bytes"] > 0
        assert fz["preempt_count"] == 1
        assert seen_frozen["host_rows"] == 1
        assert seen_frozen["host_row_bytes"] == fz["bytes"]
        assert seen_frozen["can_preempt"] is True
        # And a scheduler-free engine has no sched surface at all.
        eng.close()
        plain = ServingEngine(init_params(_cfg(), seed=0), _cfg(),
                              batch=2, kv_pages=24)
        assert plain.debug_sched() is None
        plain.close()


class TestChaosPreemptSpill:
    def test_crash_at_preempt_spill_replays_bitexact(self):
        # The fault fires after the victim is chosen and BEFORE its
        # pages are gathered: the crashed incarnation never moved KV,
        # the supervisor rebuilds (fresh scheduler heaps via
        # spawn_successor), and replay-from-scratch produces the same
        # bytes as an undisturbed FIFO drain.
        cfg = _cfg()
        params = init_params(cfg, seed=0)
        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, cfg.vocab, 9).astype(np.int32)
                   for _ in range(3)]
        plan = faults.install(faults.FaultPlan())
        crash = plan.add(site="preempt_spill", action="raise")
        # Round throttle: the driver thread keeps decoding between the
        # occupancy poll below and the staggered submit, and with warm
        # jit caches a loaded 1-core CI box can blow through the batch
        # jobs' whole occupancy window in one scheduling hiccup — then
        # nothing is left to preempt and the fault never fires. A 20 ms
        # floor per round keeps the round clock ~2x coarser than the
        # poll tick, making the stagger deterministic.
        plan.add(site="decode_round", action="delay", delay_s=0.02,
                 round_every=1, max_fires=1000)
        try:
            eng = ServingEngine(params, cfg, batch=2, round_steps=4,
                                seed=7, kv_pages=24,
                                host_kv_bytes=1 << 24,
                                scheduler=Scheduler())
            fe = EngineFrontend(eng).start()
            h0 = fe.submit(prompts[0], 40, request_id=0,
                           sched_class="batch")
            h1 = fe.submit(prompts[1], 40, request_id=1,
                           sched_class="batch")
            deadline = time.perf_counter() + 60.0
            while (fe.engine.round_idx < 1
                   and time.perf_counter() < deadline):
                time.sleep(0.01)
            h2 = fe.submit(prompts[2], 6, request_id=2,
                           sched_class="interactive")
            toks = {h.request_id:
                    list(map(int, h.result(120.0).tokens))
                    for h in (h0, h1, h2)}
            assert crash.fires == 1
            assert fe.restarts == 1
            fe.drain(30.0)
        finally:
            faults.reset()
        ref, _, _, _ = _staggered_run({}, False, sched=False)
        assert toks == ref


class TestSchedSloSmoke:
    def test_bench_tenants_line_and_slo_gate(self, tmp_path):
        # End-to-end CI form: the whole tenants artifact (bit-exact
        # matrix + chaos arm + contention drain) through
        # tools/slo_check.py --metrics-key metrics_tenants against the
        # committed baseline (docs/serving.md §8).
        env = dict(os.environ, BENCH_FORCE_CPU="1", BENCH_RETRIES="1")
        r = subprocess.run(
            [sys.executable, "bench.py", "--config", "tenants"],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=_REPO)
        assert r.returncode == 0, r.stderr[-800:]
        lines = [json.loads(l) for l in r.stdout.strip().splitlines()]
        (line,) = [d for d in lines
                   if d["metric"] == "serving_tenants_sched"]
        assert line["bit_exact"] is True
        assert line["bit_exact_spec"] is True
        assert line["chaos_bit_exact"] is True
        assert line["chaos_fault_fires"] >= 1
        assert line["chaos_engine_restarts"] >= 1
        assert line["value"] >= 3.0  # chat p99 wait-rounds improvement
        assert line["batch_throughput_ratio"] >= 0.8
        assert line["preempts"] >= 1 and line["resumes"] >= 1
        assert line["recompiles_after_warmup"] == 0
        assert line["recompiles_after_warmup_off"] == 0
        m = line["metrics"]
        assert m["counters"]["serving_kv_row_spills_total"] >= 1
        assert m["counters"]["serving_kv_row_restores_total"] >= 1
        assert m["histograms"][
            'serving_sched_queue_wait_seconds{cls="interactive"}'][
            "count"] >= 1
        artifact = tmp_path / "tenants_artifact.jsonl"
        artifact.write_text(r.stdout)
        slo = subprocess.run(
            [sys.executable, "tools/slo_check.py", str(artifact),
             "--metrics-key", "metrics_tenants"],
            capture_output=True, text=True, timeout=60, cwd=_REPO)
        assert slo.returncode == 0, slo.stdout + slo.stderr
        assert "SLO OK" in slo.stdout


class TestServerPlumbing:
    def _boot(self, *extra):
        return subprocess.Popen(
            [sys.executable, "-m", "marlin_tpu.serving.server",
             "--port", "0", "--force-cpu", "--d-model", "32",
             "--n-layers", "1", "--vocab", "64", "--max-len", "64",
             "--batch", "2", "--round-steps", "2", "--kv-pages", "12",
             "--host-kv-bytes", str(1 << 20), *extra],
            cwd=_REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)

    def test_sched_server_surface_end_to_end(self):
        # --sched end to end: /debug/sched narrates the class table,
        # POST carries tenant/sched_class, an unknown class maps to
        # 400, and the drain still seals clean on SIGTERM.
        proc = self._boot("--sched")
        try:
            line = proc.stdout.readline()
            assert line.startswith("SERVING "), line
            port = int(line.strip().split("port=")[1])
            base = f"http://127.0.0.1:{port}"
            with urllib.request.urlopen(f"{base}/debug/sched",
                                        timeout=30.0) as resp:
                snap = json.loads(resp.read())
            assert [c["name"] for c in snap["classes"]] == \
                ["interactive", "batch", "best_effort"]
            assert snap["default_class"] == "interactive"
            body = json.dumps({"prompt": list(range(1, 9)), "steps": 4,
                               "tenant": "acme",
                               "sched_class": "interactive"}).encode()
            with urllib.request.urlopen(urllib.request.Request(
                    f"{base}/v1/generate", data=body,
                    method="POST"), timeout=60.0) as resp:
                out = json.loads(resp.read())
            assert out["status"] == "done" and len(out["tokens"]) == 4
            bad = json.dumps({"prompt": [1, 2], "steps": 2,
                              "sched_class": "gold"}).encode()
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(urllib.request.Request(
                    f"{base}/v1/generate", data=bad,
                    method="POST"), timeout=30.0)
            assert err.value.code == 400
            assert "unknown scheduling class" in \
                err.value.read().decode()
            proc.send_signal(signal.SIGTERM)
            assert proc.wait(60.0) == 0, proc.stderr.read()[-800:]
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(10.0)

    def test_schedless_server_404s_debug_sched(self):
        proc = self._boot()
        try:
            line = proc.stdout.readline()
            assert line.startswith("SERVING "), line
            port = int(line.strip().split("port=")[1])
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/sched",
                    timeout=30.0)
            assert err.value.code == 404
            assert "--sched" in err.value.read().decode()
            proc.send_signal(signal.SIGTERM)
            assert proc.wait(60.0) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(10.0)
