"""Pallas block-sparse GEMM tests (interpreter mode on the CPU mesh; the same
kernel compiles for TPU)."""

import numpy as np
import pytest
import jax.numpy as jnp

from marlin_tpu.ops import BlockSparse, block_sparse_matmul

BS = 8


def _block_sparse_dense(rng, rows, cols, keep=0.4):
    arr = rng.standard_normal((rows, cols)).astype(np.float32)
    for bi in range(rows // BS):
        for bj in range(cols // BS):
            if rng.random() > keep:
                arr[bi * BS : (bi + 1) * BS, bj * BS : (bj + 1) * BS] = 0
    return arr


class TestBlockSparse:
    def test_from_dense_mask(self, rng):
        arr = _block_sparse_dense(rng, 32, 24)
        b = BlockSparse.from_dense(arr, block_size=BS)
        assert b.mask.shape == (4, 3)
        expected_mask = np.array(
            [
                [np.any(arr[i * BS : (i + 1) * BS, j * BS : (j + 1) * BS])
                 for j in range(3)]
                for i in range(4)
            ]
        )
        np.testing.assert_array_equal(np.asarray(b.mask).astype(bool), expected_mask)

    def test_from_dense_pads(self, rng):
        arr = rng.standard_normal((10, 13)).astype(np.float32)
        b = BlockSparse.from_dense(arr, block_size=BS)
        assert b.shape == (16, 16)
        np.testing.assert_allclose(np.asarray(b.to_dense())[:10, :13], arr)

    def test_matmul_matches_dense(self, rng):
        arr = _block_sparse_dense(rng, 40, 24)
        b = BlockSparse.from_dense(arr, block_size=BS)
        a = rng.standard_normal((16, 40)).astype(np.float32)
        out = block_sparse_matmul(jnp.asarray(a), b)
        np.testing.assert_allclose(np.asarray(out), a @ arr, rtol=1e-4, atol=1e-4)

    def test_matmul_uneven_m_padded(self, rng):
        arr = _block_sparse_dense(rng, 24, 16)
        b = BlockSparse.from_dense(arr, block_size=BS)
        a = rng.standard_normal((11, 24)).astype(np.float32)
        out = block_sparse_matmul(jnp.asarray(a), b)
        assert out.shape == (11, 16)
        np.testing.assert_allclose(np.asarray(out), a @ arr, rtol=1e-4, atol=1e-4)

    def test_all_zero_matrix(self, rng):
        b = BlockSparse.from_dense(np.zeros((16, 16), np.float32), block_size=BS)
        a = rng.standard_normal((8, 16)).astype(np.float32)
        out = block_sparse_matmul(jnp.asarray(a), b)
        np.testing.assert_allclose(np.asarray(out), 0.0)

    def test_matmul_under_jit_tracer_mask(self, rng):
        # Inside jit the mask is a tracer -> full-grid masked kernel path.
        import jax

        arr = _block_sparse_dense(rng, 24, 16)
        a = rng.standard_normal((16, 24)).astype(np.float32)

        @jax.jit
        def f(a, data, mask):
            from marlin_tpu.ops.block_sparse import BlockSparse

            return block_sparse_matmul(a, BlockSparse(data, mask, BS))

        b = BlockSparse.from_dense(arr, block_size=BS)
        out = f(jnp.asarray(a), b.data, b.mask)
        np.testing.assert_allclose(np.asarray(out), a @ arr, rtol=1e-4, atol=1e-4)

    def test_empty_column_blocks(self, rng):
        # A column with zero nonzero blocks must come out exactly zero even
        # though the gather grid still visits it once (dummy revisit step).
        arr = _block_sparse_dense(rng, 32, 24, keep=1.0)
        arr[:, 8:16] = 0  # middle block-column entirely empty
        b = BlockSparse.from_dense(arr, block_size=BS)
        a = rng.standard_normal((8, 32)).astype(np.float32)
        out = block_sparse_matmul(jnp.asarray(a), b)
        np.testing.assert_allclose(np.asarray(out), a @ arr, rtol=1e-4, atol=1e-4)
        np.testing.assert_array_equal(np.asarray(out)[:, 8:16], 0.0)

    def test_dimension_mismatch(self, rng):
        b = BlockSparse.from_dense(np.ones((16, 16), np.float32), block_size=BS)
        with pytest.raises(ValueError):
            block_sparse_matmul(jnp.ones((4, 8), jnp.float32), b)

    def test_mask_shape_contract(self):
        with pytest.raises(ValueError):
            BlockSparse(jnp.ones((16, 16)), jnp.ones((3, 2)), BS)


class TestBf16Accumulation:
    def test_bf16_output_accumulates_f32_across_k(self, rng):
        # B filled with 1 + 2^-6 (exact in bf16): each 128-wide k-block
        # contributes exactly 130.0 per output element; the exact product
        # over 8 k-steps is 1040.0 (bf16-representable). A bf16
        # (7-mantissa-bit) running accumulator rounds intermediates and
        # lands on 1032.0 (verified by simulating the old += path); the f32
        # VMEM scratch keeps every partial exact.
        import jax.numpy as jnp

        n, bs = 1024, 128
        val = 1.0 + 2.0 ** -6
        b = BlockSparse(jnp.full((n, n), val, jnp.bfloat16),
                        jnp.ones((n // bs, n // bs), bool), bs)
        a = jnp.ones((n, n), jnp.bfloat16)
        out = np.asarray(block_sparse_matmul(a, b), np.float64)
        assert out.min() == out.max() == 1040.0, (out.min(), out.max())


class TestGradients:
    def test_grads_match_dense_oracle(self, rng):
        # Forward = Pallas kernel; backward = closed-form recompute. Against
        # autodiff through the dense zero-masked product: dA exact, dB equal
        # on masked blocks and zero elsewhere.
        import jax
        import jax.numpy as jnp

        n, bs = 128, 32
        mask = rng.random((n // bs, n // bs)) < 0.5
        bdata = jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
        b = BlockSparse(bdata, jnp.asarray(mask), bs)
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float32)

        def loss_kernel(a, data):
            bb = BlockSparse.__new__(BlockSparse)
            bb.data, bb.mask, bb.block_size = data, b.mask, bs
            bb._host_mask, bb._gather_lists_cache = b._host_mask, None
            return jnp.sum(block_sparse_matmul(a, bb) ** 2)

        def loss_dense(a, data):
            return jnp.sum(jnp.dot(a, data) ** 2)

        ga = jax.grad(loss_kernel, argnums=(0, 1))(a, b.data)
        gd = jax.grad(loss_dense, argnums=(0, 1))(a, b.data)
        np.testing.assert_allclose(np.asarray(ga[0]), np.asarray(gd[0]),
                                   rtol=1e-4, atol=1e-4)
        # dB agrees on masked blocks; zero on unmasked (dense oracle's
        # gradient there is nonzero but the parameter doesn't exist).
        bm = np.repeat(np.repeat(mask, bs, 0), bs, 1)
        np.testing.assert_allclose(np.asarray(ga[1])[bm],
                                   np.asarray(gd[1])[bm], rtol=1e-4, atol=1e-4)
        assert np.all(np.asarray(ga[1])[~bm] == 0)
