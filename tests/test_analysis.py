"""marlint (marlin_tpu/analysis) tests.

Two layers:

* FIXTURE tests — every rule is proven to both FIRE (re-introducing the
  exact prior bug that motivated it: PR 2's ``device_get`` engine
  fetch, PR 6's unlocked ``_prefilling`` insert, PR 7's
  pre-``sys.modules`` exec loader, ...) and STAY QUIET on the
  sanctioned pattern next to it. Fixtures go through the same
  ``core.analyze`` pipeline as the real run (annotations, suppressions,
  path scoping, baseline split).
* The FULL-REPO gate — the same entry point ``make lint`` runs
  (``analysis.main``): zero non-baselined findings over marlin_tpu/,
  benchlib/, and tools/ in < 10 s, a clean tests/ sweep, and the
  baseline-staleness check (every committed baseline key still matches
  a live finding).

No jax/engine imports needed for the fixture layer — the analyzer is
stdlib-only by design.
"""

import ast
import json
import textwrap
import time

import pytest

from marlin_tpu import analysis
from marlin_tpu.analysis import callgraph, core, flow
from marlin_tpu.analysis import cfg as cfg_mod
from marlin_tpu.analysis.rules import rules_by_name


def run_lint(tmp_path, files, rules=None, baseline=None):
    """Write ``files`` ({relpath: source}) under tmp_path and analyze
    them with the given rule subset (default: all)."""
    targets = []
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        top = rel.split("/")[0]
        if top not in targets:
            targets.append(top)
    return core.analyze(tmp_path, targets, rules_by_name(rules),
                        baseline=baseline)


def names(report):
    return [(f.rule, f.line) for f in report.findings]


def rules_hit(report):
    return {f.rule for f in report.findings}


# ---------------------------------------------------------------------
# donation-fetch
# ---------------------------------------------------------------------

ENGINE_FIXTURE = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    class Engine:
        def __init__(self, batch):
            self._cache = jnp.zeros((batch,))  # donated-buffer
            self._buf = jnp.zeros((batch, 8))  # donated-buffer

        def retire_bug(self):
            # PR 2's zero-copy-view bug, verbatim shape: fetch the
            # donated token buffer with device_get.
            return jax.device_get(self._buf)

        def retire_bug_asarray(self):
            return np.asarray(self._cache)

        def retire_ok(self):
            return np.array(self._buf)  # the sanctioned explicit copy

        def fetch_locals_ok(self, filled_d, done_d):
            # Round RESULTS are fresh (non-donated) outputs — fetching
            # them with device_get is the engine's sanctioned fence.
            return jax.device_get((filled_d, done_d))
"""


class TestDonationFetch:
    def test_pr2_device_get_engine_fetch_flagged_by_name(self, tmp_path):
        rep = run_lint(tmp_path, {"serving/engine.py": ENGINE_FIXTURE},
                       rules=["donation-fetch"])
        assert len(rep.findings) == 2
        lines = {f.line for f in rep.findings}
        msgs = " ".join(f.message for f in rep.findings)
        assert "jax.device_get() on donated buffer `._buf`" in msgs
        assert "np.asarray() on donated buffer `._cache`" in msgs
        assert "np.array" in msgs  # the fix is named in the message
        # the two sanctioned fetches stay quiet
        src = (tmp_path / "serving/engine.py").read_text()
        ok_lines = [i + 1 for i, ln in enumerate(src.splitlines())
                    if "retire_ok" in ln or "fetch_locals_ok" in ln]
        assert not (lines & set(range(min(ok_lines), max(ok_lines) + 3)))

    def test_cross_file_fetch_is_covered(self, tmp_path):
        # The frontend touching eng._buf is covered by the ENGINE's
        # declaration — the annotation is global by attribute name.
        rep = run_lint(tmp_path, {
            "serving/engine.py": ENGINE_FIXTURE,
            "serving/frontend.py": """
                import numpy as np

                def fanout(eng):
                    return np.asarray(eng._buf)  # BUG
            """,
        }, rules=["donation-fetch"])
        assert any(f.path == "serving/frontend.py" for f in rep.findings)

    def test_paged_pool_fetch_is_covered(self, tmp_path):
        # The PR-9 paged pool: ``PagePool.pages`` is a donated buffer
        # (every paged round/prefill re-threads it), so the PR-2 CPU
        # zero-copy-view hazard applies to it VERBATIM — a device_get
        # of the pool (even through an engine attribute chain) must
        # fire; the np.array snapshot the tests use stays quiet.
        rep = run_lint(tmp_path, {"serving/pages.py": """
            import jax
            import jax.numpy as jnp
            import numpy as np

            class PagePool:
                def __init__(self, n):
                    self.pages = jnp.zeros((n, 16))  # donated-buffer

                def snapshot_bug(self):
                    return jax.device_get(self.pages)

                def snapshot_ok(self):
                    return np.array(self.pages)

            def debug_bug(eng):
                # cross-attribute chain: the engine's pool is the SAME
                # declared buffer by name.
                return np.asarray(eng.page_pool.pages)
        """}, rules=["donation-fetch"])
        msgs = " ".join(f.message for f in rep.findings)
        assert len(rep.findings) == 2
        assert "jax.device_get() on donated buffer `.pages`" in msgs
        assert "np.asarray() on donated buffer `.pages`" in msgs

    def test_suppression_and_baseline(self, tmp_path):
        files = {"serving/engine.py": ENGINE_FIXTURE.replace(
            "return jax.device_get(self._buf)",
            "return jax.device_get(self._buf)  "
            "# marlint: disable=donation-fetch")}
        rep = run_lint(tmp_path, files, rules=["donation-fetch"])
        assert len(rep.findings) == 1  # only the asarray one remains
        # baseline the survivor: new empty, key matched, nothing stale
        key = rep.findings[0].key
        rep2 = run_lint(tmp_path, files, rules=["donation-fetch"],
                        baseline={key})
        assert not rep2.new and [f.key for f in rep2.baselined] == [key]
        assert not rep2.stale
        # a stale key (bug fixed, entry left behind) is reported
        rep3 = run_lint(tmp_path, files, rules=["donation-fetch"],
                        baseline={key, "donation-fetch::gone.py::x:y"})
        assert rep3.stale == ["donation-fetch::gone.py::x:y"]
        assert not rep3.clean

    def test_suppression_on_wrapped_statement_tail(self, tmp_path):
        # The docs' natural trailing-comment position: the statement
        # wraps, the disable comment lands on the LAST line, the
        # finding anchors on the FIRST — still suppressed.
        rep = run_lint(tmp_path, {"serving/engine.py": ENGINE_FIXTURE + """
        def wrapped_fetch(eng):
            return np.asarray(
                eng._buf)  # marlint: disable=donation-fetch
        """}, rules=["donation-fetch"])
        assert not any("wrapped_fetch" in f.message for f in rep.findings)
        assert len(rep.findings) == 2  # the fixture's own two bugs only

    def test_keys_are_stable_across_runs(self, tmp_path):
        rep1 = run_lint(tmp_path, {"serving/engine.py": ENGINE_FIXTURE},
                        rules=["donation-fetch"])
        rep2 = run_lint(tmp_path, {"serving/engine.py": ENGINE_FIXTURE},
                        rules=["donation-fetch"])
        assert [f.key for f in rep1.findings] == \
            [f.key for f in rep2.findings]
        assert all("::" in f.key for f in rep1.findings)


# ---------------------------------------------------------------------
# guarded-by
# ---------------------------------------------------------------------

GUARDED_FIXTURE = """
    import threading

    class Engine:
        def __init__(self):
            self._submit_lock = threading.Lock()
            self.requests = {}          # guarded-by: _submit_lock
            self._prefilling = {}       # guarded-by: _submit_lock

        def admit_bug(self, row, job):
            # PR 6's unlocked _prefilling insert, verbatim shape.
            self._prefilling[row] = job

        def admit_ok(self, row, job):
            with self._submit_lock:
                self._prefilling[row] = job
                self.requests[row] = job

        def read_bug(self):
            return len(self.requests)

        def helper_locked(self):  # marlint: holds=_submit_lock
            return sorted(self._prefilling)

        def escaping_closure_bug(self):
            with self._submit_lock:
                def cb():
                    # A nested def may outlive the lock scope: held
                    # locks do NOT propagate into it.
                    return self._prefilling.popitem()
                return cb
"""


class TestGuardedBy:
    def test_pr6_unlocked_prefilling_insert_flagged_by_name(self,
                                                            tmp_path):
        rep = run_lint(tmp_path, {"serving/engine.py": GUARDED_FIXTURE},
                       rules=["guarded-by"])
        by_msg = {f.message for f in rep.findings}
        assert any("_prefilling" in m and "Engine.admit_bug" in m
                   and "_submit_lock" in m for m in by_msg), by_msg
        assert any("requests" in m and "Engine.read_bug" in m
                   for m in by_msg)
        assert any("Engine.escaping_closure_bug" in m for m in by_msg)
        # locked writes and the holds-annotated helper stay quiet
        assert not any("admit_ok" in m or "helper_locked" in m
                       for m in by_msg)
        assert len(rep.findings) == 3

    def test_init_is_exempt_and_reads_count(self, tmp_path):
        rep = run_lint(tmp_path, {"serving/x.py": """
            import threading

            class Q:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = []  # guarded-by: _lock
                    self._q.append(1)  # construction: exempt

                def peek_bug(self):
                    return self._q[0]  # a READ also needs the lock
        """}, rules=["guarded-by"])
        assert len(rep.findings) == 1
        assert "Q.peek_bug" in rep.findings[0].message

    def test_holds_in_body_does_not_exempt_the_method(self, tmp_path):
        # A holds= comment on a NESTED def (or anywhere in the body)
        # is that def's contract only — the enclosing method's unlocked
        # touches still flag.
        rep = run_lint(tmp_path, {"serving/x.py": """
            import threading

            class E:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._state = {}  # guarded-by: _lock

                def outer_bug(self):
                    def helper():  # marlint: holds=_lock
                        return len(self._state)  # OK: helper's contract
                    return self._state.copy()    # BUG: outer holds nothing
        """}, rules=["guarded-by"])
        assert len(rep.findings) == 1
        assert "E.outer_bug" in rep.findings[0].message

    def test_dataclass_field_declaration(self, tmp_path):
        rep = run_lint(tmp_path, {"serving/q.py": """
            import threading
            from collections import deque
            from dataclasses import dataclass, field

            @dataclass
            class AdmissionQueue:
                _q: deque = field(default_factory=deque)  # guarded-by: _lock

                def __post_init__(self):
                    self._lock = threading.Lock()

                def submit_ok(self, req):
                    with self._lock:
                        self._q.append(req)

                def submit_bug(self, req):
                    self._q.append(req)
        """}, rules=["guarded-by"])
        assert len(rep.findings) == 1
        assert "submit_bug" in rep.findings[0].message


# ---------------------------------------------------------------------
# deterministic-serving
# ---------------------------------------------------------------------


class TestDeterministicServing:
    def test_ambient_rng_and_wall_clock_flagged(self, tmp_path):
        rep = run_lint(tmp_path, {"marlin_tpu/serving/engine.py": """
            import random
            import time
            import numpy as np

            def schedule(reqs):
                if random.random() < 0.5:       # BUG: ambient draw
                    reqs = list(reqs)
                    np.random.shuffle(reqs)     # BUG: ambient shuffle
                deadline = time.time() + 5      # BUG: clock as control
                t0 = time.perf_counter()        # OK: sanctioned clock
                return reqs, deadline, t0

            def emit(runlog):
                runlog.emit("drain", t_wall=time.time())  # timestamp-only

            def workload(vocab):
                rng = random.Random(0)          # OK: seeded = replayable
                return [rng.randrange(vocab) for _ in range(4)]

            def job_inputs(seed):
                # OK: a per-job PRNG stream folded from the job seed —
                # deterministic given the job, exactly the replay
                # contract (serving/jobs.generate_inputs).
                rng = np.random.default_rng(
                    np.random.SeedSequence([0x6D78, seed]))
                return rng.standard_normal(4)

            def entropy_stream():
                return np.random.SeedSequence()  # BUG: OS entropy
        """}, rules=["deterministic-serving"])
        msgs = [f.message for f in rep.findings]
        assert len(msgs) == 4, msgs
        assert any("random.random" in m for m in msgs)
        assert any("np.random.shuffle" in m for m in msgs)
        assert any("time.time" in m for m in msgs)
        assert any("SeedSequence" in m for m in msgs)

    def test_timestamp_only_on_wrapped_statement_tail(self, tmp_path):
        # Like disable=, the annotation's natural position is the
        # wrapped statement's LAST line; the call anchors on the first.
        rep = run_lint(tmp_path, {"marlin_tpu/serving/s.py": """
            import time

            def emit(runlog):
                runlog.emit("begin", t_wall=time.time(),
                            extra=1)  # timestamp-only
        """}, rules=["deterministic-serving"])
        assert not rep.findings

    def test_rule_is_path_scoped(self, tmp_path):
        # The same nondeterminism OUTSIDE the serving/replay scope
        # (bench workload generators, examples) is fine.
        rep = run_lint(tmp_path, {"benchlib/gen.py": """
            import random, time

            def workload():
                return random.random(), time.time()
        """}, rules=["deterministic-serving"])
        assert not rep.findings


# ---------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------


class TestRetraceHazard:
    def test_host_conversions_inside_jit(self, tmp_path):
        rep = run_lint(tmp_path, {"marlin_tpu/kern.py": """
            import functools
            import time
            import jax
            import jax.numpy as jnp

            @functools.partial(jax.jit, static_argnames=("steps",))
            def round_fn(buf, filled, steps):
                n = int(filled)            # BUG: traced int()
                t = time.perf_counter()    # BUG: trace-time clock
                v = buf[0].item()          # BUG: host sync
                k = int(steps)             # OK: static_argnames
                m = float(buf.shape[0])    # OK: shapes are static
                return buf + n + v + k + m

            def host_fn(x):
                return int(x)              # OK: not a jit body
        """}, rules=["retrace-hazard"])
        msgs = [f.message for f in rep.findings]
        assert len(msgs) == 3, msgs
        assert any(".item()" in m for m in msgs)
        assert any("int()" in m for m in msgs)
        assert any("time.perf_counter" in m for m in msgs)

    def test_traced_value_mixed_into_shape_arithmetic_flags(self,
                                                            tmp_path):
        # `.shape` subterms are static, but a traced value MIXED into
        # the expression keeps the conversion a hazard.
        rep = run_lint(tmp_path, {"marlin_tpu/kern3.py": """
            import jax

            @jax.jit
            def f(buf, filled):
                n = int(filled + buf.shape[0])   # BUG: filled is traced
                m = int(buf.shape[0] * 2)        # OK: pure shape math
                return buf + n + m
        """}, rules=["retrace-hazard"])
        assert len(rep.findings) == 1
        assert rep.findings[0].line == 6

    def test_call_form_and_inner_defs(self, tmp_path):
        # jax.jit(f) closures and while_loop body defs are traced too.
        rep = run_lint(tmp_path, {"marlin_tpu/kern2.py": """
            import jax
            import jax.numpy as jnp

            def make(n):
                def f(x):
                    def body(c):
                        return c + float(x[0])  # BUG: traced float()
                    return jax.lax.while_loop(
                        lambda c: c < n, body, x.sum())
                return jax.jit(f)
        """}, rules=["retrace-hazard"])
        assert len(rep.findings) == 1
        assert "float()" in rep.findings[0].message


# ---------------------------------------------------------------------
# exec-loader
# ---------------------------------------------------------------------


class TestExecLoader:
    def test_pr7_pre_sys_modules_loader_flagged_by_name(self, tmp_path):
        rep = run_lint(tmp_path, {"tools/loader.py": """
            import importlib.util
            import sys

            def load_bug(path):
                # PR 7's dataclass-annotation crash, verbatim shape.
                spec = importlib.util.spec_from_file_location("m", path)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
                return mod

            def load_ok(path):
                spec = importlib.util.spec_from_file_location("m", path)
                mod = importlib.util.module_from_spec(spec)
                sys.modules["m"] = mod  # BEFORE exec: the contract
                spec.loader.exec_module(mod)
                return mod

            def load_bug_late(path):
                spec = importlib.util.spec_from_file_location("m", path)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
                sys.modules["m"] = mod  # too late
                return mod
        """}, rules=["exec-loader"])
        assert len(rep.findings) == 2
        assert {"load_bug", "load_bug_late"} == {
            f.message.split(" in ")[1].split(":")[0]
            for f in rep.findings}
        assert all("sys.modules" in f.message for f in rep.findings)

    def test_unrelated_modules_dict_does_not_vouch(self, tmp_path):
        # A local dict named `modules` is NOT a sys.modules
        # registration; `from sys import modules` (aliased or not) is.
        rep = run_lint(tmp_path, {"tools/l3.py": """
            import importlib.util

            def load_bug(path):
                modules = {}
                modules["m"] = object()  # unrelated local dict
                spec = importlib.util.spec_from_file_location("m", path)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
                return mod
        """, "tools/l4.py": """
            import importlib.util
            from sys import modules

            def load_ok(path):
                spec = importlib.util.spec_from_file_location("m", path)
                mod = importlib.util.module_from_spec(spec)
                modules["m"] = mod  # the real sys.modules, imported
                spec.loader.exec_module(mod)
                return mod
        """}, rules=["exec-loader"])
        assert len(rep.findings) == 1
        assert rep.findings[0].path == "tools/l3.py"

    def test_exec_compile_form(self, tmp_path):
        rep = run_lint(tmp_path, {"tools/l2.py": """
            def load(src, g):
                exec(compile(src, "<mem>", "exec"), g)
        """}, rules=["exec-loader"])
        assert len(rep.findings) == 1
        assert "exec(compile)" in rep.findings[0].message


# ---------------------------------------------------------------------
# export-integrity
# ---------------------------------------------------------------------


class TestExportIntegrity:
    def test_stale_exports_flagged(self, tmp_path):
        rep = run_lint(tmp_path, {
            "pkg/__init__.py": """
                from .mod import real_fn, gone_fn
                from . import missing_sub

                __all__ = ["real_fn", "never_bound"]
            """,
            "pkg/mod.py": """
                def real_fn():
                    return 1
            """,
        }, rules=["export-integrity"])
        msgs = " | ".join(f.message for f in rep.findings)
        assert len(rep.findings) == 3, msgs
        assert "gone_fn" in msgs
        assert "missing_sub" in msgs
        assert "never_bound" in msgs

    def test_clean_package_is_quiet(self, tmp_path):
        rep = run_lint(tmp_path, {
            "pkg/__init__.py": """
                from . import mod
                from .mod import real_fn

                __all__ = ["mod", "real_fn"]
            """,
            "pkg/mod.py": """
                def real_fn():
                    return 1
            """,
        }, rules=["export-integrity"])
        assert not rep.findings

    def test_function_locals_do_not_count_as_bindings(self, tmp_path):
        # A name bound INSIDE a function (even under `if`) is not a
        # module binding — importing it is an ImportError at runtime
        # and must flag.
        rep = run_lint(tmp_path, {
            "pkg/__init__.py": """
                from .mod import helper
            """,
            "pkg/mod.py": """
                if True:
                    def outer():
                        helper = 1
                        return helper
            """,
        }, rules=["export-integrity"])
        assert len(rep.findings) == 1
        assert "helper" in rep.findings[0].message

    def test_package_submodule_reexport_is_quiet(self, tmp_path):
        # `from .sub import real_mod` where real_mod is a SUBMODULE of
        # package sub/ (not a binding of sub/__init__.py) is a valid
        # re-export — a gone submodule still flags.
        rep = run_lint(tmp_path, {
            "pkg/__init__.py": """
                from .sub import real_mod, gone_mod
            """,
            "pkg/sub/__init__.py": "",
            "pkg/sub/real_mod.py": "X = 1\n",
        }, rules=["export-integrity"])
        assert len(rep.findings) == 1
        assert "gone_mod" in rep.findings[0].message


# ---------------------------------------------------------------------
# the dataflow core (cfg.py / flow.py / callgraph.py)
# ---------------------------------------------------------------------


def _describe(src):
    tree = ast.parse(textwrap.dedent(src))
    return cfg_mod.build_cfg(tree.body).describe()


class TestCFG:
    def test_if_else_joins(self):
        assert _describe("""
            a = 1
            if a:
                b = 2
            else:
                c = 3
            d = 4
        """) == [
            "B0: stmt use -> B2,B3",
            "B2: stmt -> B4",
            "B3: stmt -> B4",
            "B4: stmt -> exit",
        ]

    def test_while_break_continue(self):
        assert _describe("""
            while cond:
                if x:
                    break
                y = 1
                continue
            z = 2
        """) == [
            "B0: - -> B2",
            "B2: use -> B4,B3",   # header -> body, after
            "B3: stmt -> exit",   # after-loop
            "B4: use -> B5,B6",   # if x
            "B5: - -> B3",        # break jumps to after
            "B6: stmt -> B2",     # continue jumps to header
        ]

    def test_try_except_finally_edges(self):
        # Coarse exception model: the try body may fall into the
        # handler; both routes reach the finally block.
        assert _describe("""
            try:
                a = 1
            except ValueError:
                b = 2
            finally:
                c = 3
        """) == [
            "B0: - -> B2",
            "B2: stmt -> B3,B4",
            "B3: stmt -> exit",   # finally
            "B4: stmt -> B3",     # handler -> finally
        ]

    def test_with_emits_enter_exit_and_return_skips_exit_event(self):
        # The in-with return leaves the scope directly; only the
        # fall-through path replays with_exit before the tail.
        assert _describe("""
            with lk:
                if p:
                    return 1
            tail = 2
        """) == [
            "B0: use with_enter use -> B2,B3",
            "B2: stmt -> exit",
            "B3: with_exit stmt -> exit",
        ]

    def test_code_after_return_has_no_predecessor(self):
        # Dead code lands in a block no edge reaches — dataflow sees
        # TOP there and every rule stays quiet on it by construction.
        cfg = cfg_mod.build_cfg(ast.parse("return 1\ndead = 2").body)
        dead = [b for b in cfg.blocks
                if b is not cfg.exit and b is not cfg.entry and b.events]
        assert len(dead) == 1
        preds = {s.idx for b in cfg.blocks for s in b.succs}
        assert dead[0].idx not in preds


class TestLockLattice:
    A, B = ("self", "_a"), ("self", "_b")

    def test_acquire_release_roundtrip(self):
        s = flow.lock_acquire(flow.EMPTY_LOCKS, self.A)
        s = flow.lock_acquire(s, self.B)
        assert flow.held_refs(s) == (self.A, self.B)
        s = flow.lock_release(s, self.A)
        assert flow.held_refs(s) == (self.B,)
        assert flow.lock_release(s, self.B) == flow.EMPTY_LOCKS

    def test_meet_takes_min_counts(self):
        # Must-analysis: a lock held on only ONE branch is NOT held at
        # the join — exactly the branch-acquired guarded-by bug.
        one = flow.lock_acquire(flow.EMPTY_LOCKS, self.A)
        two = flow.lock_acquire(one, self.A)
        assert flow.lock_meet(one, flow.EMPTY_LOCKS) == flow.EMPTY_LOCKS
        assert flow.lock_meet(two, one) == one
        assert flow.lock_meet(one, flow.lock_acquire(
            flow.EMPTY_LOCKS, self.B)) == flow.EMPTY_LOCKS

    def test_top_is_meet_identity(self):
        one = flow.lock_acquire(flow.EMPTY_LOCKS, self.A)
        assert flow.lock_meet(flow.TOP, one) == one
        assert flow.meet_intersect(flow.TOP, frozenset({"x"})) == \
            frozenset({"x"})
        assert flow.meet_intersect(frozenset({"x", "y"}),
                                   frozenset({"y"})) == frozenset({"y"})
        assert flow.meet_union(frozenset({"x"}), flow.TOP) == \
            frozenset({"x"})
        assert flow.meet_union(frozenset({"x"}),
                               frozenset({"y"})) == frozenset({"x", "y"})


CALLGRAPH_FIXTURE = """
    import json
    import threading

    class RunLog:
        def __init__(self):
            self._lock = threading.Lock()

        def emit(self, rec):
            with self._lock:
                return json.dumps(rec)

        def dumps(self, rec):
            return str(rec)

        def seal(self):
            with self._lock:
                self._sink.flush()

        def flush(self):
            with self._lock:
                pass

    def helper():
        return 1

    def caller():
        return helper()
"""


class TestCallResolution:
    def _graph(self, tmp_path):
        p = tmp_path / "obs.py"
        src = textwrap.dedent(CALLGRAPH_FIXTURE)
        p.write_text(src)
        idx = callgraph.ProjectIndex()
        idx.add_source(core.SourceFile(p, "obs.py", src))
        return idx.resolved()

    def test_self_call_resolves_to_declaring_class(self, tmp_path):
        g = self._graph(tmp_path)
        assert g.resolve_call("self", "dumps", "obs.py", "RunLog") == \
            ("obs.py", "RunLog.dumps")

    def test_bare_call_resolves_same_module_only(self, tmp_path):
        g = self._graph(tmp_path)
        assert g.resolve_call("bare", "helper", "obs.py", None) == \
            ("obs.py", "helper")
        assert g.resolve_call("bare", "nope", "obs.py", None) is None

    def test_imported_receiver_refuses_method_match(self, tmp_path):
        # json.dumps name-matches the unique method RunLog.dumps; the
        # module receiver is the evidence that it is NOT one.
        g = self._graph(tmp_path)
        assert g.resolve_call("attr", "dumps", "obs.py", "RunLog",
                              recv="json") is None

    def test_stdlib_proto_names_never_match_by_name_alone(self, tmp_path):
        # self._sink.flush() must not resolve to RunLog.flush — the
        # file-object protocol names carry no type evidence.
        g = self._graph(tmp_path)
        assert "flush" in callgraph.STDLIB_PROTO_METHODS
        assert g.resolve_call("attr", "flush", "obs.py", "RunLog") is None

    def test_unresolvable_dynamic_calls_degrade_to_no_finding(self,
                                                              tmp_path):
        # handlers[k]() / getattr(...)() under a lock: no resolution,
        # no finding, no crash — and never exit-code-2 material.
        rep = run_lint(tmp_path, {"serving/dyn.py": """
            import threading

            class D:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._handlers = {}

                def dispatch(self, kind):
                    with self._lock:
                        fn = self._handlers[kind]
                        fn()
                        getattr(self, "on_" + kind)()
        """}, rules=["lock-order", "blocking-under-lock", "guarded-by"])
        assert not rep.findings and not rep.parse_errors


# ---------------------------------------------------------------------
# guarded-by v2 (flow-sensitive lock-sets)
# ---------------------------------------------------------------------


class TestGuardedByFlow:
    def test_branch_acquired_lock_is_not_held_at_join(self, tmp_path):
        rep = run_lint(tmp_path, {"serving/g2.py": """
            import threading

            class E:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = []  # guarded-by: _lock

                def join_bug(self, flag):
                    if flag:
                        self._lock.acquire()
                    self._q.append(1)

                def both_arms_ok(self, flag):
                    with self._lock:
                        if flag:
                            self._q.append(1)
                        else:
                            self._q.append(2)
        """}, rules=["guarded-by"])
        assert len(rep.findings) == 1
        assert "join_bug" in rep.findings[0].message

    def test_holds_helper_called_without_lock_flags(self, tmp_path):
        rep = run_lint(tmp_path, {"serving/g3.py": """
            import threading

            class E:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = []  # guarded-by: _lock

                def call_bug(self):
                    return self._helper()

                def call_ok(self):
                    with self._lock:
                        return self._helper()

                def _helper(self):  # marlint: holds=_lock
                    return len(self._q)
        """}, rules=["guarded-by"])
        assert len(rep.findings) == 1
        m = rep.findings[0].message
        assert "E.call_bug calls _helper()" in m and "holds=_lock" in m


# ---------------------------------------------------------------------
# donation-fetch v2 (alias-aware taint)
# ---------------------------------------------------------------------


class TestDonationFetchFlow:
    def test_alias_of_donated_buffer_fires(self, tmp_path):
        rep = run_lint(tmp_path, {"serving/al.py": """
            import jax.numpy as jnp
            import numpy as np

            class Eng:
                def __init__(self):
                    self._buf = jnp.zeros((4,))  # donated-buffer

                def alias_bug(self):
                    buf = self._buf
                    return np.asarray(buf)

                def realias_ok(self):
                    buf = self._buf
                    buf = np.zeros(4)
                    return np.asarray(buf)
        """}, rules=["donation-fetch"])
        assert len(rep.findings) == 1
        m = rep.findings[0].message
        assert "`buf`, an alias of donated buffer `._buf`" in m

    def test_alias_through_returning_method_fires(self, tmp_path):
        rep = run_lint(tmp_path, {"serving/al2.py": """
            import jax.numpy as jnp
            import numpy as np

            class Eng:
                def __init__(self):
                    self._buf = jnp.zeros((4,))  # donated-buffer

                def view(self):
                    return self._buf

            def fetch_bug(eng):
                b = eng.view()
                return np.asarray(b)
        """}, rules=["donation-fetch"])
        assert len(rep.findings) == 1
        assert "alias of donated buffer" in rep.findings[0].message


# ---------------------------------------------------------------------
# retrace-hazard v2 (static-set propagation)
# ---------------------------------------------------------------------


class TestRetraceHazardFlow:
    def test_tracedness_propagates_through_locals(self, tmp_path):
        rep = run_lint(tmp_path, {"marlin_tpu/rt.py": """
            import jax

            @jax.jit
            def f(logits):
                x = logits[0]
                bad = int(x)          # BUG: x aliases a traced value
                n = logits.shape[0]
                ok = int(n)           # OK: n is shape-derived = static
                return bad + ok
        """}, rules=["retrace-hazard"])
        assert len(rep.findings) == 1
        assert rep.findings[0].line == 7


# ---------------------------------------------------------------------
# exec-loader v2 (path-sensitive domination)
# ---------------------------------------------------------------------


class TestExecLoaderFlow:
    def test_one_arm_registration_does_not_dominate(self, tmp_path):
        rep = run_lint(tmp_path, {"tools/pl.py": """
            import importlib.util
            import sys

            def load_one_arm_bug(path, fast):
                spec = importlib.util.spec_from_file_location("m", path)
                mod = importlib.util.module_from_spec(spec)
                if fast:
                    sys.modules["m"] = mod
                spec.loader.exec_module(mod)
                return mod

            def load_both_arms_ok(path, fast):
                spec = importlib.util.spec_from_file_location("m", path)
                mod = importlib.util.module_from_spec(spec)
                if fast:
                    sys.modules["m"] = mod
                else:
                    sys.modules["m"] = mod
                spec.loader.exec_module(mod)
                return mod
        """}, rules=["exec-loader"])
        assert len(rep.findings) == 1
        assert "load_one_arm_bug" in rep.findings[0].message
        assert "EVERY path" in rep.findings[0].message


# ---------------------------------------------------------------------
# lock-order (project-wide deadlock cycles)
# ---------------------------------------------------------------------


class TestLockOrder:
    def test_two_lock_inversion_prints_both_witness_paths(self, tmp_path):
        # THE acceptance fixture: opposite acquisition orders across
        # two methods; the finding names the cycle and prints one
        # witness acquisition path per edge.
        rep = run_lint(tmp_path, {"fleet/inv.py": """
            import threading

            class Router:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a:
                        with self._b:
                            return 1

                def backward(self):
                    with self._b:
                        with self._a:
                            return 2
        """}, rules=["lock-order"])
        assert len(rep.findings) == 1
        m = rep.findings[0].message
        assert "lock-order inversion between Router._a and Router._b" in m
        assert ("path 1: Router.forward (fleet/inv.py:11) holds "
                "Router._a -> acquires Router._b") in m
        assert ("path 2: Router.backward (fleet/inv.py:16) holds "
                "Router._b -> acquires Router._a") in m

    def test_consistent_order_is_quiet(self, tmp_path):
        rep = run_lint(tmp_path, {"fleet/ok.py": """
            import threading

            class Router:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a:
                        with self._b:
                            return 1

                def also_forward(self):
                    with self._a:
                        with self._b:
                            return 2
        """}, rules=["lock-order"])
        assert not rep.findings

    def test_self_deadlock_through_call_vs_rlock(self, tmp_path):
        # Plain Lock re-acquired via self.m() while held: 1-cycle,
        # guaranteed deadlock, witness names the call chain. The same
        # shape on an RLock is reentrant and stays quiet.
        rep = run_lint(tmp_path, {"fleet/re.py": """
            import threading

            class A:
                def __init__(self):
                    self._rl = threading.RLock()

                def outer(self):
                    with self._rl:
                        return self.inner()

                def inner(self):
                    with self._rl:
                        return 1

            class B:
                def __init__(self):
                    self._lk = threading.Lock()

                def outer(self):
                    with self._lk:
                        return self.inner()

                def inner(self):
                    with self._lk:
                        return 1
        """}, rules=["lock-order"])
        assert len(rep.findings) == 1
        m = rep.findings[0].message
        assert "non-reentrant lock B._lk" in m and "self-deadlock" in m
        assert "via B.inner" in m


# ---------------------------------------------------------------------
# blocking-under-lock
# ---------------------------------------------------------------------


BLOCKING_FIXTURE = """
    import threading
    import time

    class W:
        def __init__(self):
            self._lock = threading.Lock()
            self._cv = threading.Condition()

        def stall_bug(self):
            with self._lock:
                time.sleep(1.0)

        def cv_ok(self):
            # wait() RELEASES the condition's own lock — the
            # sanctioned pattern, never a stall.
            with self._cv:
                self._cv.wait()

        def deliberate_ok(self):
            with self._lock:
                time.sleep(0.1)  # marlint: allow-blocking=serializing is the point

        def chain_bug(self):
            with self._lock:
                self._spin()

        def _spin(self):
            time.sleep(2.0)
"""


class TestBlockingUnderLock:
    def test_direct_chain_and_exemptions(self, tmp_path):
        rep = run_lint(tmp_path, {"serving/blk.py": BLOCKING_FIXTURE},
                       rules=["blocking-under-lock"])
        msgs = [f.message for f in rep.findings]
        assert len(msgs) == 2, msgs
        assert any("blocking time.sleep() while holding W._lock in "
                   "W.stall_bug" in m for m in msgs)
        assert any("call to W._spin() while holding W._lock in "
                   "W.chain_bug reaches blocking time.sleep "
                   "(via W._spin)" in m for m in msgs)
        # cv_ok and deliberate_ok are quiet; the annotation is COUNTED
        # (an annotation, not a suppression — the zero-suppression
        # gate stays satisfiable).
        assert not any("cv_ok" in m or "deliberate_ok" in m for m in msgs)
        assert rep.stats["blocking-under-lock"]["annotations"] == 1
        assert rep.n_suppressed == 0


# ---------------------------------------------------------------------
# --stats / --jobs / cache (core plumbing)
# ---------------------------------------------------------------------


class TestStatsAndCache:
    def test_stats_flag_prints_per_rule_table(self, tmp_path, capsys):
        (tmp_path / "blk.py").write_text(textwrap.dedent(BLOCKING_FIXTURE))
        rc = analysis.main(["--root", str(tmp_path), "--no-baseline",
                            "--stats", "blk.py"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "rule" in out and "annotations" in out
        assert "blocking-under-lock" in out
        assert "files: 1" in out and "wall:" in out

    def test_content_hash_cache_hits_on_second_run(self, tmp_path):
        files = {"serving/blk.py": BLOCKING_FIXTURE,
                 "serving/g2.py": GUARDED_FIXTURE}
        rep1 = run_lint(tmp_path, files)
        rep2 = run_lint(tmp_path, files)
        assert rep2.n_files == len(files)
        assert rep2.cache_hits == rep2.n_files
        assert names(rep1) == names(rep2)

    def test_jobs_flag_matches_sequential_findings(self, tmp_path):
        # --jobs forks workers; run it out of process (this pytest
        # process carries jax) and compare the JSON verdict with the
        # sequential run over the same tree.
        import subprocess
        import sys
        for rel, src in {"serving/blk.py": BLOCKING_FIXTURE,
                         "serving/eng.py": ENGINE_FIXTURE}.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(src))
        argv = [sys.executable, "-m", "marlin_tpu.analysis",
                "--root", str(tmp_path), "--no-baseline", "--json",
                "serving"]
        seq = subprocess.run(argv, capture_output=True, text=True)
        par = subprocess.run(argv + ["--jobs", "2"],
                             capture_output=True, text=True)
        assert seq.returncode == par.returncode == 1
        d_seq, d_par = json.loads(seq.stdout), json.loads(par.stdout)
        key = lambda d: sorted((f["rule"], f["path"], f["line"])
                               for f in d["findings"])
        assert key(d_seq) == key(d_par) and d_par["files"] == 2


# ---------------------------------------------------------------------
# the full-repo tier-1 gate
# ---------------------------------------------------------------------


class TestFullRepoGate:
    def test_repo_is_clean_via_the_make_lint_entry_point(self, capsys):
        # THE gate: the exact entry point `make lint` runs, default
        # targets (marlin_tpu/ benchlib/ tools/) + committed baseline.
        # Zero non-baselined findings, zero stale baseline entries,
        # exit 0 — and the acceptance bound: < 10 s on CPU.
        t0 = time.perf_counter()
        rc = analysis.main([])
        dt = time.perf_counter() - t0
        out = capsys.readouterr().out
        assert rc == 0, f"marlint found violations:\n{out}"
        assert dt < 10.0, f"marlint took {dt:.1f}s (acceptance: < 10 s)"

    def test_tests_tree_is_clean_too(self):
        # The by-path loader sweep (PR 7's bug class lived in tests/):
        # the whole tests tree passes every rule, no baseline needed.
        root = core.Path(analysis.cli.REPO_ROOT)
        rep = core.analyze(root, ["tests"], rules_by_name(None))
        assert not rep.findings, "\n".join(
            f.text() for f in rep.findings)
        assert not rep.parse_errors

    def test_baseline_staleness_contract(self):
        # Every committed baseline key must still match a live finding
        # (an empty baseline is trivially fresh — and is the policy).
        root = core.Path(analysis.cli.REPO_ROOT)
        baseline_path = root / "tools" / "marlint_baseline.json"
        keys = core.load_baseline(baseline_path)
        rep = core.analyze(root, list(core.DEFAULT_TARGETS),
                           rules_by_name(None), baseline=keys)
        assert not rep.stale, (
            f"stale baseline entries (fixed findings whose keys were "
            f"left behind — remove them): {rep.stale}")
        assert not rep.new, "\n".join(f.text() for f in rep.new)
        # Policy: ZERO suppressions in product code (tests/fixtures may
        # use disable= to stage bugs). A real FP becomes a fixture plus
        # a precision fix, not a disable comment; a deliberate blocking
        # hold uses allow-blocking=, which is an annotation, not a
        # suppression — so this stays 0 without losing the escape hatch.
        assert rep.n_suppressed == 0, rep.stats

    def test_cli_surfaces(self, capsys):
        assert analysis.main(["--list-rules"]) == 0
        listing = capsys.readouterr().out
        for rule in ("donation-fetch", "guarded-by",
                     "deterministic-serving", "retrace-hazard",
                     "exec-loader", "export-integrity"):
            assert rule in listing
        rc = analysis.main(["--json", "--no-baseline"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0 and doc["clean"] and doc["files"] > 50
        # unknown rule name -> internal-error exit code (2), not a crash
        assert analysis.main(["--rules", "nope"]) == 2

    def test_overlapping_targets_analyze_each_file_once(self, tmp_path):
        (tmp_path / "serving").mkdir()
        (tmp_path / "serving" / "e.py").write_text(
            textwrap.dedent(ENGINE_FIXTURE))
        rep = core.analyze(tmp_path, ["serving", "serving/e.py"],
                           rules_by_name(["donation-fetch"]))
        assert rep.n_files == 1
        assert len(rep.findings) == 2  # not doubled

    def test_write_baseline_round_trip(self, tmp_path, capsys):
        # --write-baseline accepts the current findings; the re-run is
        # exit 0 with every finding baselined; fixing the bug then
        # makes the entry STALE (exit 1) — the full workflow.
        src = textwrap.dedent(ENGINE_FIXTURE)
        (tmp_path / "eng.py").write_text(src)
        base = tmp_path / "base.json"
        argv = ["--root", str(tmp_path), "eng.py",
                "--baseline", str(base)]
        assert analysis.main(argv + ["--write-baseline"]) == 0
        assert analysis.main(argv) == 0  # all baselined
        out = capsys.readouterr().out
        assert "(baselined)" in out
        (tmp_path / "eng.py").write_text(
            src.replace("jax.device_get(self._buf)",
                        "np.array(self._buf)"))
        assert analysis.main(argv) == 1  # fixed finding -> stale key
        assert "STALE" in capsys.readouterr().out

    def test_internal_error_exit_code(self, tmp_path, monkeypatch):
        # A crashing rule must surface as exit 2 (the Makefile's
        # "internal error" arm), never as a silent 0.
        class Broken(core.Rule):
            name = "broken"
            description = "boom"

            def check(self, sf, ctx):
                raise RuntimeError("boom")

        monkeypatch.setattr(analysis.cli, "ALL_RULES", (Broken(),))
        monkeypatch.setattr(
            "marlin_tpu.analysis.cli.rules_by_name",
            lambda names=None: [Broken()])
        (tmp_path / "x.py").write_text("pass\n")
        assert analysis.main(
            ["--root", str(tmp_path), "--no-baseline", "x.py"]) == 2
