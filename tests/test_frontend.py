"""HTTP serving frontend tests (marlin_tpu/serving/frontend.py +
server.py; docs/frontend.md).

The PR-5 acceptance claims, each pinned mechanically:

* CONCURRENCY — the admission queue and ``engine.submit`` survive >= 8
  producer threads racing the driver with EXACT accounting: no request
  lost, duplicated, or retired twice, and the ``serving_*_total``
  counters/queue-depth gauge agree with the ground truth to the unit.
* EXACTNESS THROUGH THE STACK — a streamed token sequence is
  byte-identical to the blocking response and to an in-process
  ``engine.run()`` of the same prompts/seeds: the bridge and the HTTP
  framing add transport, never reordering.
* BACKPRESSURE AS STATUS — queue full maps to 429 + Retry-After,
  draining to 503, malformed to 400, queue-deadline expiry to 504.
* GRACEFUL DRAIN — SIGTERM (subprocess) / ``begin_drain`` (in-process)
  completes in-flight requests, 503s new ones, seals the runlog with a
  terminal ``drain_complete`` + flush (the tail is ON DISK), exits 0.

Everything runs on the tiny CPU-mesh knobs; the bench smoke at the
bottom runs the real ``bench.py --config http`` subprocess and holds
its artifact to the committed SLO baseline's HTTP block.
"""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from marlin_tpu.models import TransformerConfig, init_params
from marlin_tpu.obs.metrics import MetricsRegistry
from marlin_tpu.obs.runlog import RunLog
from marlin_tpu.serving import (AdmissionQueue, EngineFrontend,
                                MatrixService, QueueClosed, QueueFull,
                                Request, Scheduler, ServingEngine, serve)
from marlin_tpu.serving.jobs import validate_job

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    # Register BEFORE exec (the importlib contract): dataclasses in the
    # tool resolve their string annotations via sys.modules.
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _cfg(**kw):
    base = dict(vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                max_len=64)
    base.update(kw)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return init_params(cfg, seed=0), cfg


def _prompts(cfg, n, length=16, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, cfg.vocab, length).astype(np.int32)
            for _ in range(n)]


def _golden(params, cfg, prompts, steps, **eng_kw):
    eng = ServingEngine(params, cfg, **eng_kw)
    for p in prompts:
        eng.submit(p, steps)
    return {r.request_id: list(map(int, r.tokens)) for r in eng.run()}


# -- queue + engine concurrency (satellite: AdmissionQueue safety) ----


class TestQueueConcurrency:
    def test_producers_vs_consumer_exact_accounting(self):
        q = AdmissionQueue(max_pending=10_000)
        n_threads, per = 8, 200
        accepted = [[] for _ in range(n_threads)]

        def producer(t):
            for i in range(per):
                rid = t * per + i
                q.submit(Request(request_id=rid, steps=1,
                                 prompt=np.zeros(4, np.int32)))
                accepted[t].append(rid)

        popped = []
        stop = threading.Event()

        def consumer():
            while not stop.is_set() or len(q):
                req, expired = q.pop_ready(0)
                assert not expired
                if req is not None:
                    popped.append(req.request_id)

        threads = [threading.Thread(target=producer, args=(t,))
                   for t in range(n_threads)]
        c = threading.Thread(target=consumer)
        c.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        c.join()
        all_accepted = sorted(sum(accepted, []))
        assert sorted(popped) == all_accepted  # nothing lost
        assert len(set(popped)) == len(popped)  # nothing duplicated
        assert len(q) == 0

    def test_backpressure_races_never_overfill(self):
        q = AdmissionQueue(max_pending=4)
        outcomes = []
        lock = threading.Lock()
        barrier = threading.Barrier(8)

        def producer(t):
            barrier.wait()  # maximal collision
            for i in range(25):
                try:
                    q.submit(Request(request_id=t * 25 + i, steps=1,
                                     prompt=np.zeros(4, np.int32)))
                    ok = True
                except QueueFull:
                    ok = False
                with lock:
                    outcomes.append(ok)
                    # The invariant a torn len-check would break:
                    assert len(q) <= 4

        threads = [threading.Thread(target=producer, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        n_ok = sum(outcomes)
        assert n_ok >= 4  # the queue did accept up to its cap
        drained = 0
        while q.pop_ready(0)[0] is not None:
            drained += 1
        assert drained == min(n_ok, 4) == 4

    def test_wallclock_deadline_drops_at_pop(self):
        q = AdmissionQueue()
        now = time.perf_counter()
        q.submit(Request(request_id=0, steps=1,
                         prompt=np.zeros(4, np.int32),
                         deadline_time=now - 1.0))  # already past
        q.submit(Request(request_id=1, steps=1,
                         prompt=np.zeros(4, np.int32),
                         deadline_time=now + 60.0))
        got, expired = q.pop_ready(0)
        assert got.request_id == 1
        assert [r.request_id for r in expired] == [0]
        assert expired[0].status == "timeout"


class TestEngineConcurrentSubmitters:
    def test_eight_producers_exact_request_accounting(self, model):
        """The satellite pin: 8 producer threads race the driver; no
        request is lost, duplicated, or retired twice, and the metric
        mirrors stay exact to the unit."""
        params, cfg = model
        reg = MetricsRegistry()
        eng = ServingEngine(params, cfg, batch=4, round_steps=4,
                            max_pending=512, metrics_registry=reg)
        fe = EngineFrontend(eng).start()
        n_threads, per = 8, 6
        handles = [[] for _ in range(n_threads)]
        prompts = _prompts(cfg, n_threads * per)
        barrier = threading.Barrier(n_threads)

        def producer(t):
            barrier.wait()
            for i in range(per):
                h = fe.submit(prompts[t * per + i], steps=3)
                handles[t].append(h)

        threads = [threading.Thread(target=producer, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        flat = sum(handles, [])
        results = [h.result(60.0) for h in flat]
        assert fe.drain(30.0)
        n = n_threads * per
        rids = [r.request_id for r in results]
        assert len(set(rids)) == n  # no dup ids, none lost
        assert all(r.status == "done" for r in results)
        assert all(len(r.tokens) == 3 for r in results)
        # Retired exactly once: the ledger agrees with ground truth.
        assert eng.stats.n_completed == n
        assert eng.stats.n_timeout == 0
        assert reg.counter("serving_submitted_total").value == n
        assert reg.counter("serving_completed_total").value == n
        assert reg.counter("serving_tokens_out_total").value == 3 * n
        assert reg.gauge("serving_queue_depth").value == 0
        assert len(eng.requests) == 0  # ownership fully transferred
        # And exactness survived the stampede: every prompt's tokens
        # match a solo engine run of the same workload.
        gold = _golden(params, cfg, prompts, 3, batch=4, round_steps=4)
        by_prompt = {tuple(map(int, prompts[i])): gold[i]
                     for i in range(n)}
        for h, r in zip(flat, results):
            assert list(map(int, r.tokens)) \
                == by_prompt[tuple(map(int, r.prompt))]


# -- drain semantics (satellite: runlog flush + drain_complete) -------


class TestDrainRunlog:
    def test_drain_flushes_jsonl_and_emits_terminal_ledger(
            self, model, tmp_path):
        params, cfg = model
        path = tmp_path / "runlog.jsonl"
        eng = ServingEngine(params, cfg, batch=2, round_steps=4,
                            runlog=RunLog(maxlen=8, path=path),
                            metrics_registry=MetricsRegistry())
        prompts = _prompts(cfg, 5)
        for p in prompts:
            eng.submit(p, 6)
        eng.step()  # mid-flight: rows live, queue non-empty
        assert eng.slots.n_occupied > 0
        finished = eng.drain()
        assert len(finished) == 5
        # Replay the on-disk JSONL: every line parses, the submit ->
        # admit -> complete narrative is whole for every request even
        # though the in-memory deque (maxlen=8) long since dropped the
        # head, and the terminal event carries the final ledger.
        lines = [json.loads(l)
                 for l in path.read_text().strip().splitlines()]
        assert len(lines) == eng.runlog.n_emitted  # nothing buffered
        assert lines[-1]["kind"] == "drain_complete"
        ledger = lines[-1]["ledger"]
        assert ledger["completed"] == 5
        assert ledger["admitted"] == 5
        assert ledger == eng.stats.summary()
        for kind in ("submit", "admit", "complete"):
            assert {e["request_id"] for e in lines
                    if e["kind"] == kind} == set(range(5)), kind
        assert len(eng.runlog) <= 8  # deque stayed bounded throughout

    def test_drain_complete_is_emitted_exactly_once(self, model):
        params, cfg = model
        eng = ServingEngine(params, cfg, batch=2, round_steps=4,
                            metrics_registry=MetricsRegistry())
        eng.submit(_prompts(cfg, 1)[0], 2)
        eng.drain()
        eng.run()  # idempotent: a later run() must not re-seal
        eng.drain()
        assert len(eng.runlog.events("drain_complete")) == 1

    def test_open_queue_run_does_not_seal(self, model):
        params, cfg = model
        eng = ServingEngine(params, cfg, batch=2, round_steps=4,
                            metrics_registry=MetricsRegistry())
        eng.submit(_prompts(cfg, 1)[0], 2)
        eng.run()  # drains to idle, but the queue is still OPEN
        assert eng.runlog.events("drain_complete") == []
        eng.submit(_prompts(cfg, 1)[0], 2)  # still accepts
        eng.drain()
        assert len(eng.runlog.events("drain_complete")) == 1


# -- the bridge, in-process -------------------------------------------


class TestEngineFrontend:
    def test_blocking_and_streaming_match_engine_run(self, model):
        params, cfg = model
        prompts = _prompts(cfg, 6)
        steps = 5
        gold = _golden(params, cfg, prompts, steps, batch=2,
                       round_steps=4)
        eng = ServingEngine(params, cfg, batch=2, round_steps=4,
                            metrics_registry=MetricsRegistry())
        fe = EngineFrontend(eng).start()
        stream_handles = [fe.submit(p, steps, stream=True)
                          for p in prompts[:3]]
        block_handles = [fe.submit(p, steps) for p in prompts[3:]]
        streamed = []
        for h in stream_handles:
            toks = []
            for chunk in h.chunks():
                toks.extend(int(t) for t in chunk)
            streamed.append(toks)
            assert h.result(10.0).status == "done"
        for i, h in enumerate(stream_handles):
            assert streamed[i] == gold[i]
            # The stream IS the blocking array, chunked.
            assert streamed[i] == list(map(int, h.result(0.1).tokens))
        for i, h in enumerate(block_handles):
            assert list(map(int, h.result(30.0).tokens)) == gold[3 + i]
        assert fe.drain(30.0)
        assert not fe.ready  # drained frontends report unready

    def test_deadline_s_times_out_queued_request(self, model):
        params, cfg = model
        eng = ServingEngine(params, cfg, batch=1, round_steps=4,
                            metrics_registry=MetricsRegistry())
        fe = EngineFrontend(eng).start()
        long_h = fe.submit(_prompts(cfg, 1)[0], 32)  # hogs the slot
        short_h = fe.submit(_prompts(cfg, 2)[1], 2, deadline_s=0.002)
        assert short_h.result(30.0).status == "timeout"
        assert long_h.result(60.0).status == "done"
        assert eng.stats.n_timeout == 1
        fe.drain(10.0)


# -- the HTTP layer (tier-1 smoke satellite) --------------------------


@pytest.fixture(scope="module")
def http_server(model):
    params, cfg = model
    srv = serve(params, cfg, port=0, batch=2, round_steps=4,
                max_pending=8, seed=0).start_background()
    yield srv
    try:
        srv.close_now()
    except OSError:
        pass


@pytest.fixture(scope="module")
def client_mod():
    return _load_tool("serving_client")


class TestHTTPServer:
    def test_blocking_request_matches_golden(self, http_server, model,
                                             client_mod):
        params, cfg = model
        prompts = _prompts(cfg, 2, seed=7)
        gold = _golden(params, cfg, prompts, 4, batch=2, round_steps=4)
        c = client_mod.ServingClient(port=http_server.port)
        r = c.generate(prompts[0], 4, request_id="my-id-123")
        assert r["code"] == 200 and r["status"] == "done"
        assert r["tokens"] == gold[0]
        assert r["emitted"] == 4
        assert r["x_request_id"] == "my-id-123"  # caller id echoed
        assert r["x_engine_request_id"] is not None
        # Without a caller id, the engine id is the echo.
        r2 = c.generate(prompts[1], 4)
        assert r2["x_request_id"] == r2["x_engine_request_id"]

    def test_streaming_bitexact_with_blocking(self, http_server, model,
                                              client_mod):
        params, cfg = model
        prompt = _prompts(cfg, 1, seed=11)[0]
        c = client_mod.ServingClient(port=http_server.port)
        st = c.stream(prompt, 6)
        bl = c.generate(prompt, 6)
        assert st["code"] == bl["code"] == 200
        assert st["tokens"] == bl["tokens"]
        assert st["status"] == "done" and st["emitted"] == 6
        assert st["ttft_s"] > 0
        assert len(st["chunks"]) >= 1

    def test_metrics_healthz_readyz(self, http_server, client_mod):
        c = client_mod.ServingClient(port=http_server.port)
        m = c.metrics()
        assert m["code"] == 200
        for series in ("serving_http_requests_total",
                       "serving_http_ttft_seconds",
                       "serving_ttft_seconds", "serving_queue_depth"):
            assert series in m["text"], series
        assert any(k.startswith("serving_http_responses_total")
                   for k in m["samples"])
        assert c.healthz()["code"] == 200
        rz = c.readyz()
        assert rz["code"] == 200 and rz["ready"] and rz["driver_alive"]

    def test_timing_block_attributes_latency(self, http_server,
                                             client_mod):
        """The PR-6 tentpole at the HTTP surface: every generate
        response carries the `timing` block, whose contiguous phases
        sum exactly to the engine-side total (one monotonic clock), and
        the stream's terminal done event carries the same block."""
        c = client_mod.ServingClient(port=http_server.port)
        prompt = _prompts(_cfg(), 1, seed=21)[0]
        r = c.generate(prompt, 4)
        assert r["code"] == 200
        t = r["timing"]
        for k in ("queue_wait_s", "admit_s", "decode_s", "total_s",
                  "http_total_s"):
            assert k in t, t
        contiguous = t["queue_wait_s"] + t["admit_s"] + t["decode_s"]
        # Fields are rounded to 1 us server-side; the identity holds to
        # rounding, far inside the 5% acceptance tolerance.
        assert contiguous == pytest.approx(t["total_s"], abs=5e-6)
        assert t["http_total_s"] >= t["total_s"] - 5e-3  # same clock
        st = c.stream(prompt, 4)
        assert st["code"] == 200
        ts = st["timing"]
        assert ts["queue_wait_s"] + ts["admit_s"] + ts["decode_s"] \
            == pytest.approx(ts["total_s"], abs=5e-6)
        assert "http_ttft_s" in ts and "stream_delivery_s" in ts
        # The phase histograms are scrapeable, labeled, with HELP.
        m = c.metrics()
        assert 'serving_phase_seconds_bucket{phase="decode"' in m["text"]
        assert "# HELP serving_phase_seconds" in m["text"]
        assert "# HELP cost_model_drift_ratio" in m["text"]

    def test_debug_endpoints(self, http_server, client_mod):
        """GET /debug/engine, /debug/requests/<id>, /debug/trace: the
        point-in-time introspection surface (docs/frontend.md)."""
        c = client_mod.ServingClient(port=http_server.port)
        r = c.generate(_prompts(_cfg(), 1, seed=23)[0], 3)
        assert r["code"] == 200
        code, body, _ = c._get("/debug/engine")
        assert code == 200
        dbg = json.loads(body)
        assert dbg["batch"] == 2 and dbg["round"] > 0
        assert dbg["frontend"]["alive"] is True
        assert "cost_model_drift" in dbg and "stats" in dbg
        assert dbg["stats"]["completed"] >= 1
        code, body, _ = c._get(f"/debug/requests/{r['request_id']}")
        assert code == 200
        info = json.loads(body)
        assert info["status"] == "done"
        ph = info["phases"]
        assert ph["queue_wait"] + ph["admit"] + ph["decode"] \
            == pytest.approx(ph["total"], rel=1e-6, abs=1e-9)
        code, body, _ = c._get("/debug/requests/987654")
        assert code == 404
        code, body, _ = c._get("/debug/requests/not-an-id")
        assert code == 400
        code, body, _ = c._get("/debug/trace")
        assert code == 200
        doc = json.loads(body)  # valid Chrome-trace JSON by round-trip
        assert "traceEvents" in doc
        code, body, _ = c._get("/debug/trace?exemplars=1")
        assert code == 200 and "traceEvents" in json.loads(body)

    def test_bad_requests_map_to_400_and_404(self, http_server,
                                             client_mod):
        import http.client

        c = client_mod.ServingClient(port=http_server.port)
        # steps beyond max_len: engine validation -> 400
        r = c.generate([1, 2, 3], 10_000)
        assert r["code"] == 400 and "error" in r
        conn = http.client.HTTPConnection("127.0.0.1", http_server.port,
                                          timeout=10)
        try:
            conn.request("POST", "/v1/generate", b"{not json",
                         {"Content-Type": "application/json"})
            assert conn.getresponse().status == 400
        finally:
            conn.close()
        code, _, _ = c._get("/nope")
        assert code == 404

    def test_queue_full_maps_to_429_with_retry_after(self, model,
                                                     client_mod):
        params, cfg = model
        srv = serve(params, cfg, port=0, batch=1, round_steps=4,
                    max_pending=1, seed=0).start_background()
        try:
            c = client_mod.ServingClient(port=srv.port)
            prompts = _prompts(cfg, 10, seed=3)
            results = [None] * 10

            def fire(i):
                results[i] = client_mod.ServingClient(
                    port=srv.port).generate(prompts[i], 24)

            threads = [threading.Thread(target=fire, args=(i,))
                       for i in range(10)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            codes = [r["code"] for r in results]
            shed = [r for r in results if r["code"] == 429]
            assert shed, codes  # 1 slot + 1 pending cannot hold 10
            assert all(r["retry_after"] is not None for r in shed)
            served = [r for r in results if r["code"] == 200]
            assert served  # accepted requests completed under the burst
            assert all(len(r["tokens"]) == 24 for r in served)
            assert len(shed) + len(served) == 10
        finally:
            srv.begin_drain(30.0)

    def test_drain_completes_in_flight_and_503s_new(self, model,
                                                    client_mod):
        """In-process shape of the SIGTERM contract: begin_drain mid-
        stream -> the in-flight stream finishes byte-complete, new
        submits 503, readyz flips, the runlog seals."""
        params, cfg = model
        srv = serve(params, cfg, port=0, batch=2, round_steps=2,
                    max_pending=8, seed=0).start_background()
        c = client_mod.ServingClient(port=srv.port)
        prompt = _prompts(cfg, 1, seed=5)[0]
        stream_res = {}

        def streamer():
            stream_res.update(c.stream(prompt, 24))

        st = threading.Thread(target=streamer)
        st.start()
        time.sleep(0.05)  # let the stream get in flight
        drained = {}

        def drainer():
            drained["ok"] = srv.begin_drain(60.0)

        dt = threading.Thread(target=drainer)
        dt.start()
        time.sleep(0.02)
        # New work while draining: 503 with Retry-After (the listener
        # is still up until in-flight work completes) — or, late in the
        # drain, a torn-down listener. Both are valid shed shapes; a
        # 200 would mean draining admitted new work.
        try:
            r = c.generate(prompt, 2)
            assert r["code"] == 503, r
        except (ConnectionError, OSError):
            pass
        st.join(60.0)
        dt.join(60.0)
        assert drained.get("ok") is True
        assert stream_res["code"] == 200
        assert stream_res["status"] == "done"
        assert stream_res["emitted"] == 24  # in-flight ran to the end
        assert len(stream_res["tokens"]) == 24
        kinds = [e["kind"] for e in srv.runlog.events()]
        assert "drain_complete" in kinds


class TestClientMultiTarget:
    """The fleet satellite on the client: an ordered target list with
    connect-error failover riding the existing RetryPolicy."""

    def test_parse_target_forms(self, client_mod):
        pt = client_mod.parse_target
        assert pt("10.0.0.2:8100") == ("10.0.0.2", 8100)
        assert pt(":8100") == ("127.0.0.1", 8100)
        assert pt("8100") == ("127.0.0.1", 8100)
        assert pt(8100) == ("127.0.0.1", 8100)
        assert pt(("h", 9), default_host="x") == ("h", 9)

    def test_connect_error_rotates_preferred_target(self, client_mod):
        import socket

        # Two dead ports (bound-then-closed, so nothing listens).
        dead = []
        for _ in range(2):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            dead.append(s.getsockname()[1])
            s.close()
        c = client_mod.ServingClient(
            targets=[f":{dead[0]}", f":{dead[1]}"], timeout=2.0)
        assert c.port == dead[0]
        with pytest.raises(OSError):
            c.generate([1, 2, 3], 2)
        assert c.port == dead[1]  # next call prefers the next target
        # With a policy: both attempts fail, the ledger records them,
        # and the result is a connect_error dict — not a raise (the
        # load generators keep going and count it).
        res = c.generate([1, 2, 3], 2, retry=client_mod.RetryPolicy(
            max_attempts=2, base_delay_s=0.001))
        assert res["code"] is None and "connect_error" in res
        assert res["attempts"] == 2

    def test_failover_lands_on_live_target(self, http_server, model,
                                           client_mod):
        """Dead target first, live server second: one policy retry
        lands the request on the live endpoint byte-exactly, and the
        client keeps preferring the live endpoint afterwards (no
        per-call re-probing of the dead one)."""
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        params, cfg = model
        prompts = _prompts(cfg, 2, seed=7)
        gold = _golden(params, cfg, prompts, 4, batch=2, round_steps=4)
        c = client_mod.ServingClient(
            targets=[f":{dead_port}", f":{http_server.port}"],
            timeout=30.0)
        policy = client_mod.RetryPolicy(max_attempts=3,
                                        base_delay_s=0.01)
        r = c.generate(prompts[0], 4, retry=policy)
        assert r["code"] == 200 and r["status"] == "done"
        assert r["tokens"] == gold[0]
        assert r["attempts"] == 2  # one dead hit, one live
        assert c.port == http_server.port
        # Subsequent plain call goes straight to the live target.
        r2 = c.generate(prompts[1], 4)
        assert r2["code"] == 200 and r2["tokens"] == gold[1]


class TestBaselineMetricConsistency:
    def test_every_baseline_metric_name_exists_in_live_registry(
            self, model):
        """The staleness guard: every registry metric the committed SLO
        baseline references (histogram/gauge specs, full labeled series
        names) must exist in a live registry snapshot after a smoke
        workload — rename a metric without updating the baseline and
        this fails, instead of the gate silently checking nothing.
        (slo_check already treats a missing series as a violation at
        gate time; this pins the contract at unit-test speed, for BOTH
        baseline blocks at once.)"""
        params, cfg = model
        reg = MetricsRegistry()
        # Paged + host-tiered: the baseline's metrics_host_kv block
        # references the tier's gauge/histogram series, which register
        # at tier construction (count 0 until the first restore) — a
        # tierless smoke would read them as stale.
        # Scheduled, too: the metrics_tenants block references the
        # per-class queue-wait histogram, which records at first
        # admission only when a scheduler is attached (requests land in
        # the default interactive class here).
        eng = ServingEngine(params, cfg, batch=2, round_steps=4,
                            metrics_registry=reg, kv_pages=32,
                            host_kv_bytes=1 << 20,
                            scheduler=Scheduler())
        # Matrix-serving, too: the metrics_matrix block references the
        # job-seconds histogram and the queue-depth gauge, which
        # register at MatrixService construction (docs/
        # matrix_service.md) — an LLM-only smoke would read them as
        # stale. One real job keeps the histogram honest (count >= 1).
        mx = MatrixService(metrics=reg)
        fe = EngineFrontend(eng, matrix=mx).start()
        mh = fe.submit_matrix(validate_job(
            {"op": "gemm", "shapes": [16, 8, 8], "dtype": "float32",
             "seed": 0}))
        # Streamed requests exercise the full phase surface, including
        # the frontend's stream_delivery slice.
        handles = [fe.submit(p, 4, stream=True)
                   for p in _prompts(cfg, 4, seed=31)]
        for h in handles:
            list(h.chunks())
            assert h.result(30.0).status == "done"
        _, m_meta = mh.result(30.0)
        assert m_meta["status"] == "done"
        assert fe.drain(30.0)
        snap = reg.snapshot()
        with open(os.path.join(_REPO, "tools",
                               "serving_slo_baseline.json")) as f:
            baseline = json.load(f)
        referenced = []
        for key, blocks in baseline.items():
            if key.startswith("_") or not isinstance(blocks, dict):
                continue
            for checks in blocks.values():
                for spec in checks.values():
                    if isinstance(spec, dict) and "histogram" in spec:
                        referenced.append(("histograms",
                                           spec["histogram"]))
                    if isinstance(spec, dict) and "gauge" in spec:
                        referenced.append(("gauges", spec["gauge"]))
        assert referenced  # the baseline does reference registry series
        missing = [f"{kind}:{name}" for kind, name in referenced
                   if name not in snap[kind]]
        assert not missing, (missing, sorted(snap["histograms"]),
                             sorted(snap["gauges"]))


class TestSigtermSubprocess:
    def test_sigterm_drains_and_exits_zero(self, tmp_path):
        """The acceptance criterion verbatim, against a real process:
        SIGTERM mid-stream -> the stream completes, new requests are
        shed, the runlog (file sink) carries drain_complete, exit 0."""
        sc = _load_tool("serving_client")
        runlog = tmp_path / "server_runlog.jsonl"
        proc = subprocess.Popen(
            [sys.executable, "-m", "marlin_tpu.serving.server",
             "--port", "0", "--force-cpu", "--d-model", "32",
             "--n-layers", "2", "--vocab", "64", "--max-len", "64",
             "--batch", "2", "--round-steps", "2",
             "--runlog", str(runlog)],
            cwd=_REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        try:
            line = proc.stdout.readline()
            assert line.startswith("SERVING "), line
            port = int(line.strip().split("port=")[1])
            c = sc.ServingClient(port=port, timeout=60.0)
            warm = c.generate(list(range(8)), 2)
            assert warm["code"] == 200
            stream_res = {}

            def streamer():
                stream_res.update(c.stream(list(range(8)), 24))

            st = threading.Thread(target=streamer)
            st.start()
            time.sleep(0.1)  # in flight
            proc.send_signal(signal.SIGTERM)
            time.sleep(0.02)
            try:
                shed = c.generate(list(range(8)), 2)
                assert shed["code"] == 503, shed
            except (ConnectionError, OSError):
                pass  # late in the drain the listener is already down
            st.join(60.0)
            assert stream_res.get("code") == 200, stream_res
            assert stream_res.get("emitted") == 24
            rc = proc.wait(60.0)
            assert rc == 0, proc.stderr.read()[-800:]
            assert "DRAINED" in proc.stdout.read()
            events = [json.loads(l) for l in
                      runlog.read_text().strip().splitlines()]
            assert events[-1]["kind"] == "drain_complete"
            assert events[-1]["ledger"]["completed"] >= 2
            # The offline loop closes here (tier-1 smoke of the PR-6
            # analyzer): tools/runlog_report.py replays the sealed
            # on-disk runlog this real server produced and must find a
            # clean run — report parses, zero post-warmup compiles,
            # zero anomalies, and every request's contiguous phase sum
            # within tolerance of its measured end-to-end latency.
            rep_proc = subprocess.run(
                [sys.executable, "tools/runlog_report.py", str(runlog),
                 "--json", "-"],
                capture_output=True, text=True, timeout=60, cwd=_REPO)
            assert rep_proc.returncode == 0, \
                rep_proc.stdout + rep_proc.stderr
            report = json.loads(rep_proc.stdout)
            assert report["ok"] is True
            assert report["anomalies"] == []
            assert report["sealed"] is True
            assert report["post_warmup_compiles"] == 0
            assert report["n_completed"] >= 2
            assert report["phase_sum_checked"] == report["n_completed"]
            assert report["phase_sum_max_rel_err"] <= 0.05
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(10.0)


# -- the bench artifact + SLO gate ------------------------------------


class TestHTTPBenchSmoke:
    def test_bench_http_line_and_slo_gate(self, tmp_path):
        """`bench.py --config http` end to end with tiny knobs: the
        artifact line must carry end-to-end TTFT p50/p99, inter-token
        latency, completions/s, byte-identical streams, and
        `recompiles_after_warmup == 0` READ FROM THE SCRAPED /metrics —
        then pass tools/slo_check.py against the committed baseline's
        HTTP block (the tier-1 form of the SLO gate)."""
        env = dict(
            os.environ, BENCH_FORCE_CPU="1", BENCH_RETRIES="1",
            BENCH_HTTP_D="32", BENCH_HTTP_L="2", BENCH_HTTP_REQS="6",
            BENCH_HTTP_STEPS="6", BENCH_HTTP_CONC="3",
            # round=2 so a 6-step stream spans >= 3 rounds — the
            # inter-token timeline needs more than one chunk to exist.
            BENCH_HTTP_ROUND="2",
            BENCH_HTTP_VOCAB="64", BENCH_HTTP_PEND="4",
            BENCH_HTTP_BURST="16", BENCH_HTTP_SCRAPES="5")
        r = subprocess.run(
            [sys.executable, "bench.py", "--config", "http"],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=_REPO)
        assert r.returncode == 0, r.stderr[-800:]
        lines = [json.loads(l) for l in r.stdout.strip().splitlines()]
        (line,) = [d for d in lines
                   if d["metric"] == "serving_http_frontend"]
        assert line["streams_bitexact"] is True
        assert line["recompiles_after_warmup"] == 0
        assert line["drain_ok"] is True
        assert line["completions_per_s"] > 0
        assert 0 < line["ttft_p50_s"] <= line["ttft_p99_s"]
        assert line["intertoken_mean_s"] > 0
        assert line["overload_429s"] >= 1  # the burst actually shed
        assert line["metrics_scrape_p99_s"] > 0
        # The scraped-exposition path fed the metrics block too.
        assert line["metrics"]["histograms"][
            "serving_http_ttft_seconds"]["count"] > 0
        artifact = tmp_path / "http_artifact.jsonl"
        artifact.write_text(r.stdout)
        slo = subprocess.run(
            [sys.executable, "tools/slo_check.py", str(artifact),
             "--metrics-key", "metrics_http"],
            capture_output=True, text=True, timeout=60, cwd=_REPO)
        assert slo.returncode == 0, slo.stdout + slo.stderr
        assert "SLO OK" in slo.stdout

    def test_slo_quantile_bound_helper(self):
        slo = _load_tool("slo_check")
        hist = {"count": 10, "sum": 1.0,
                "buckets": {"0.001": 4, "0.1": 5, "+Inf": 1}}
        assert slo._quantile_bound(hist, 0.10) == 0.001
        assert slo._quantile_bound(hist, 0.50) == 0.1
        assert slo._quantile_bound(hist, 0.99) == float("inf")
        # End to end through the check: p50 within 0.1 passes, p99
        # lands in +Inf and violates.
        line = {"metrics": {"histograms": {"h": hist}}}
        ok = slo._check_histogram(line, "f", {
            "histogram": "h", "quantile": 0.5, "max_quantile_s": 0.1})
        assert ok == []
        bad = slo._check_histogram(line, "f", {
            "histogram": "h", "quantile": 0.99, "max_quantile_s": 5.0})
        assert len(bad) == 1 and "p99" in bad[0]
