"""Unit tests for bench.py's harness pieces (timing + artifact contract).

The bench script is the round's perf-artifact producer (BENCH_r{N}.json);
its failure modes — a traceback instead of a parsable line, RTT-polluted
kernel timings — each cost a capture session before being fixed, so the
harness functions get the same regression coverage as library code.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

import bench


class TestScanTimed:
    def test_positive_and_finite(self):
        x = jnp.ones((64, 64), jnp.float32)
        dt = bench._scan_timed(lambda x: x @ x, x, loop=3, reps=3)
        assert np.isfinite(dt) and dt > 0

    def test_reps_one_falls_back_to_single_shot(self):
        # reps < 2 must not divide by zero (review finding): one fenced
        # scan, RTT included.
        x = jnp.ones((32, 32), jnp.float32)
        dt = bench._scan_timed(lambda x: x @ x, x, loop=2, reps=1)
        assert np.isfinite(dt) and dt > 0

    def test_extra_operands_pass_through(self):
        x = jnp.ones((32, 32), jnp.float32)
        y = jnp.full((32, 32), 2.0, jnp.float32)
        dt = bench._scan_timed(lambda a, b: a @ b, x, y, loop=2, reps=2)
        assert dt > 0


class TestTimed:
    def test_returns_result_and_caps_burst(self):
        x = jnp.ones((128, 128), jnp.float32)
        dt, r = bench._timed_r(lambda: x @ x, iters=3)
        assert dt > 0 and r.shape == (128, 128)


class TestErrorContract:
    def test_emit_error_is_parsable_json(self, capsys):
        bench._emit_error("some_config", "boom")
        line = capsys.readouterr().out.strip()
        d = json.loads(line)
        assert d["metric"] == "some_config" and d["unit"] == "error"
        assert d["error"] == "boom" and d["vs_baseline"] == 0.0

    def test_trim_err_bounds_length(self):
        e = ValueError("x" * 10_000)
        s = bench._trim_err(e, limit=100)
        assert len(s) == 100

    def test_xla_ref_survives_baseline_failure(self):
        def broken():
            raise RuntimeError("scoped vmem exceeded")

        out = bench._xla_ref({"metric": "m", "value": 1.0}, "lu", broken, 1.0)
        assert out["vs_baseline"] == 0
        assert "scoped vmem" in out["xla_lu_error"]
        assert out["value"] == 1.0  # our measurement survives

    def test_xla_ref_scopes_baseline_precision(self, monkeypatch):
        # The baseline must run under linalg_precision_scope (an ambient-
        # default bf16-pass baseline fails the same oracle bar our op is
        # held to).
        import jax

        import marlin_tpu.config as cfg_mod

        seen = []
        real = jax.default_matmul_precision

        def spy(p):
            seen.append(p)
            return real(p)

        monkeypatch.setattr(jax, "default_matmul_precision", spy)
        x = jnp.ones((16, 16), jnp.float32)
        out = bench._xla_ref({"metric": "m", "value": 1.0}, "c",
                             lambda: x @ x, 1e-9)
        assert "highest" in seen
        assert "xla_c_seconds" in out and "xla_c_error" not in out


class TestConfigsRegistry:
    def test_all_excludes_sweeps(self):
        assert "sweep" in bench.CONFIGS and "attnsweep" in bench.CONFIGS
        sweep_fns = set(bench.CONFIGS["sweep"] + bench.CONFIGS["attnsweep"])
        assert not sweep_fns & set(bench.CONFIGS["all"])

    def test_every_config_has_callable(self):
        for name, fns in bench.CONFIGS.items():
            assert fns and all(callable(f) for f in fns), name

    def test_every_artifact_config_has_cache_prefix(self):
        # Every "all" config must be replayable from captures on a dead
        # tunnel — a new config without a _CACHE_PREFIX entry would silently
        # drop out of the fallback artifact.
        for fn in bench.CONFIGS["all"]:
            assert fn.__name__ in bench._CACHE_PREFIX, fn.__name__


class TestCachedFallback:
    """Dead-tunnel artifact fallback (VERDICT r02 item 2): BENCH_r0{1,2}
    both went rc=1 because the backend was unreachable at capture time even
    though valid on-hardware lines existed in docs/bench_captures/."""

    def _write(self, path, lines):
        with open(path, "w") as f:
            for line in lines:
                f.write(json.dumps(line) + "\n")

    def test_latest_valid_line_wins(self, tmp_path):
        old = {"metric": "dense_gemm_tflops_per_chip_32k", "value": 100.0,
               "unit": "TFLOPS/chip", "vs_baseline": 1.0}
        new = dict(old, value=186.58)
        self._write(tmp_path / "a.jsonl", [old])
        self._write(tmp_path / "b.jsonl", [new])
        import os
        import time

        now = time.time()
        os.utime(tmp_path / "a.jsonl", (now - 7200, now - 7200))
        os.utime(tmp_path / "b.jsonl", (now - 60, now - 60))
        best = bench._load_cached_lines(str(tmp_path))
        assert best["headline"][1]["value"] == 186.58
        assert best["headline"][2] == "b.jsonl"

    def test_error_and_failed_oracle_lines_skipped(self, tmp_path):
        self._write(tmp_path / "c.jsonl", [
            {"metric": "lu_dist_16k_seconds", "value": 0.0, "unit": "error",
             "vs_baseline": 0, "error": "boom"},
            {"metric": "lu_dist_16k_seconds", "value": 1.2, "unit": "s",
             "vs_baseline": 0.4, "oracle_ok": False},
            {"metric": "cholesky_dist_16k_seconds", "value": 0.3, "unit": "s",
             "vs_baseline": 0.4, "oracle_ok": True},
        ])
        best = bench._load_cached_lines(str(tmp_path))
        assert "config_lu" not in best  # error + failed oracle don't count
        assert best["config_cholesky"][1]["value"] == 0.3

    def test_emit_tags_lines_and_counts(self, tmp_path, capsys):
        self._write(tmp_path / "d.jsonl", [
            {"metric": "dense_gemm_tflops_per_chip_32k", "value": 186.58,
             "unit": "TFLOPS/chip", "vs_baseline": 1.894},
        ])
        n = bench._emit_cached_results("headline", "tunnel dead",
                                       str(tmp_path))
        assert n == 1
        lines = [json.loads(l)
                 for l in capsys.readouterr().out.strip().splitlines()]
        # Status precedes the metric lines it describes: the driver records
        # the LAST stdout line as the round's parsed metric (VERDICT r04
        # weak #1 — BENCH_r04 parsed bench_run_status instead of TFLOPS).
        status = lines[0]
        assert status["metric"] == "bench_run_status"
        assert status["live"] is False and status["value"] == 1.0
        d = lines[-1]
        assert d["metric"] == "dense_gemm_tflops_per_chip_32k"
        assert d["cached"] is True and d["value"] == 186.58
        assert d["backend_error"] == "tunnel dead"
        assert d["cached_from"].endswith("d.jsonl")
        assert d["cached_age_hours"] >= 0

    def test_emit_empty_dir_returns_zero(self, tmp_path):
        assert bench._emit_cached_results("headline", "e", str(tmp_path)) == 0

    def test_real_capture_dir_covers_headline(self):
        # The shipped capture files must already satisfy the fallback for
        # the default --config, or BENCH_r03 would still go rc=1 on a dead
        # tunnel at end-of-round.
        best = bench._load_cached_lines()
        assert "headline" in best
        assert best["headline"][1]["value"] > 0

    def test_real_capture_dir_covers_most_of_all(self, capsys):
        # A dead-tunnel `--config all` run should still produce a nearly
        # complete artifact from the shipped captures. Three configs have
        # never captured on hardware: longseq (every session died first)
        # and decodeint8/decodespec (new in r05).
        n = bench._emit_cached_results("all", "test")
        lines = [json.loads(l)
                 for l in capsys.readouterr().out.strip().splitlines()]
        status = [d for d in lines if d["metric"] == "bench_run_status"]
        cached = [d for d in lines if d["metric"] != "bench_run_status"]
        assert n == len(cached) >= len(bench.CONFIGS["all"]) - 3
        for d in cached:
            assert d["cached"] is True and d["value"] > 0
        assert len(status) == 1 and status[0]["live"] is False
        # Ordering contract: status first, perf metric last (driver parses
        # the last line — BENCH_r05 must show a perf metric even on replay).
        assert lines[0]["metric"] == "bench_run_status"
        assert lines[-1]["metric"] != "bench_run_status"

    def test_live_run_emits_status_first_metric_last(self, capsys,
                                                     monkeypatch):
        # Same contract on the LIVE path: main() knows each config emits
        # exactly one line (result or error), so status can lead.
        import sys as _sys

        monkeypatch.setattr(bench, "init_backend", lambda: None)
        monkeypatch.setattr(bench.mt, "set_config", lambda **kw: None)

        def config_fake():
            return {"metric": "fake_metric_seconds", "value": 1.5,
                    "unit": "s", "vs_baseline": 1.1}

        def config_boom():
            raise RuntimeError("boom")

        monkeypatch.setitem(bench.CONFIGS, "faketest",
                            [config_fake, config_boom])
        monkeypatch.setattr(_sys, "argv", ["bench.py", "--config", "faketest"])
        with pytest.raises(SystemExit) as ei:
            bench.main()
        assert ei.value.code == 0
        lines = [json.loads(l)
                 for l in capsys.readouterr().out.strip().splitlines()]
        assert lines[0]["metric"] == "bench_run_status"
        assert lines[0]["live"] is True and lines[0]["value"] == 2.0
        # One line per config even when a config raises; last is a metric.
        assert len(lines) == 3
        assert lines[1]["metric"] == "fake_metric_seconds"
        assert lines[-1]["unit"] == "error"  # boom's parsable error line
        # Every artifact line — result AND error — carries the obs
        # metrics snapshot block (the status line does not; it is run
        # bookkeeping, not an artifact).
        assert "metrics" not in lines[0]
        for d in lines[1:]:
            assert set(d["metrics"]) == {"counters", "gauges",
                                         "histograms"}

    def test_all_error_live_run_has_no_status_line(self, capsys,
                                                   monkeypatch):
        # Review finding r05: a run where nothing measures must not carry
        # a live=True status — consumers map "status present" to "evidence
        # exists". All-error live runs stay status-free (rc=1).
        import sys as _sys

        monkeypatch.setattr(bench, "init_backend", lambda: None)
        monkeypatch.setattr(bench.mt, "set_config", lambda **kw: None)

        def config_boom():
            raise RuntimeError("boom")

        monkeypatch.setitem(bench.CONFIGS, "errtest", [config_boom])
        monkeypatch.setattr(_sys, "argv", ["bench.py", "--config", "errtest"])
        with pytest.raises(SystemExit) as ei:
            bench.main()
        assert ei.value.code == 1
        lines = [json.loads(l)
                 for l in capsys.readouterr().out.strip().splitlines()]
        assert len(lines) == 1 and lines[0]["unit"] == "error"
        assert all(d["metric"] != "bench_run_status" for d in lines)


class TestMetricsAttachment:
    def test_attach_metrics_adds_snapshot_block(self):
        from marlin_tpu.obs import metrics as om

        om.registry.counter("bench_test_counter").inc(2)
        try:
            line = bench.attach_metrics({"metric": "m", "value": 1.0})
            assert line["metrics"]["counters"]["bench_test_counter"] == 2
            json.dumps(line)  # the artifact line must stay one JSON line
        finally:
            om.registry.remove("bench_test_counter")

    def test_attach_metrics_is_idempotent(self):
        # A config that attached its own block keeps it.
        line = bench.attach_metrics({"metric": "m", "metrics": {"x": 1}})
        assert line["metrics"] == {"x": 1}


class TestServingTraceSmoke:
    def test_bench_serving_trace_prefix_line_and_slo_gate(self, tmp_path):
        # Tier-1-safe smoke (CPU mesh, tiny knobs): `bench.py --config
        # serving` must produce artifact lines carrying the metrics
        # block (counters + TTFT/per-token histograms), export a
        # Chrome/Perfetto trace JSON that json.load()s, and include the
        # shared-prefix reuse line (hit rate, reclaimed tokens, >= 1.3x
        # cache-on wall-clock, zero recompiles in both arms) — then the
        # whole artifact must pass tools/slo_check.py against the
        # COMMITTED baseline, which is how an SLO regression fails fast
        # in tier-1 instead of rounds later in a bench diff.
        import os
        import subprocess
        import sys

        trace_path = tmp_path / "serving_trace.json"
        env = dict(
            os.environ, BENCH_FORCE_CPU="1", BENCH_RETRIES="1",
            BENCH_TRACE_PATH=str(trace_path), BENCH_SRV_D="32",
            BENCH_SRV_L="2", BENCH_SRV_REQS="6", BENCH_SRV_SHORT="3",
            BENCH_SRV_LONG="10", BENCH_SRV_ROUND="4",
            BENCH_SRV_VOCAB="64", BENCH_SRV_PREQS="10",
            BENCH_SRV_PREFIX="64", BENCH_SRV_TAIL="6",
            BENCH_SRV_PSTEPS="3", BENCH_SRV_CHUNK="16",
            BENCH_SRV_POOL="2")
        r = subprocess.run(
            [sys.executable, "bench.py", "--config", "serving"],
            capture_output=True, text=True, timeout=300, env=env)
        assert r.returncode == 0, r.stderr[-800:]
        lines = [json.loads(l) for l in r.stdout.strip().splitlines()]
        (line,) = [d for d in lines
                   if d["metric"].startswith("serving_continuous")]
        m = line["metrics"]
        assert m["histograms"]["serving_ttft_seconds"]["count"] > 0
        assert m["histograms"]["serving_token_latency_seconds"][
            "count"] > 0
        assert m["counters"]["serving_completed_total"] > 0
        # The measured run compiled nothing after warmup — the artifact
        # field form of the zero-recompile guarantee.
        assert line["recompiles_after_warmup"] == 0
        assert line["trace_path"] == str(trace_path)
        with open(trace_path) as f:
            doc = json.load(f)
        evs = doc["traceEvents"]
        assert line["trace_events"] == len(evs) > 0
        names = {e["name"] for e in evs}
        assert {"serving.round", "serving.decode_round"} <= names
        for e in evs:
            assert e["ph"] == "X" and "ts" in e and "dur" in e
        # The shared-prefix reuse line (ROADMAP item 10 follow-up).
        (pline,) = [d for d in lines
                    if d["metric"] == "serving_prefix_reuse_speedup"]
        assert pline["value"] >= 1.3, pline
        assert pline["prefix_hit_rate"] >= 0.5
        assert pline["prefix_reclaimed_prefill_tokens"] > 0
        assert pline["admission_copy_bytes"] > 0  # copy-based arm bills
        assert pline["recompiles_after_warmup"] == 0
        assert pline["recompiles_after_warmup_off"] == 0
        assert pline["metrics"]["counters"][
            "serving_prefix_hits_total"] > 0
        # The paged KV line (PR 9, ROADMAP 13): zero-copy sharing beats
        # the 1.72x done-bar, admission moves ZERO KV bytes, compiles
        # stay bounded in both arms, and the allocator capacity sweep
        # holds strictly more sequences per pool byte than the row
        # cache — before sharing multiplies it further.
        (gline,) = [d for d in lines if d["metric"] == "serving_paged_kv"]
        assert gline["value"] >= 1.72, gline
        assert gline["admission_copy_bytes"] == 0
        assert gline["zero_copy_hits"] > 0
        assert gline["recompiles_after_warmup"] == 0
        assert gline["recompiles_after_warmup_off"] == 0
        assert gline["capacity_vs_row"] > 1.0
        assert gline["capacity_shared_vs_row"] > gline["capacity_vs_row"]
        assert gline["metrics"]["counters"][
            "serving_kv_zero_copy_hits_total"] > 0
        # The SLO gate, end to end: artifact -> committed baseline.
        artifact = tmp_path / "serving_artifact.jsonl"
        artifact.write_text(r.stdout)
        slo = subprocess.run(
            [sys.executable, "tools/slo_check.py", str(artifact)],
            capture_output=True, text=True, timeout=60)
        assert slo.returncode == 0, slo.stdout + slo.stderr
        assert "SLO OK" in slo.stdout


class TestCaptureSummaryHistory:
    def test_history_skips_replays_and_flags_deltas(self, tmp_path, monkeypatch):
        import importlib.util
        import sys

        spec = importlib.util.spec_from_file_location(
            "capture_summary", "tools/capture_summary.py")
        cs = importlib.util.module_from_spec(spec)
        # Register BEFORE exec (the importlib contract): dataclasses in
        # a by-path module resolve string annotations via sys.modules
        # (marlint exec-loader).
        sys.modules["capture_summary"] = cs
        spec.loader.exec_module(cs)
        monkeypatch.setattr(bench, "_CAPTURE_DIR", str(tmp_path))

        def write(name, lines):
            with open(tmp_path / name, "w") as f:
                for line in lines:
                    f.write(json.dumps(line) + "\n")

        write("r01_a.jsonl", [
            {"metric": "m_x_seconds", "value": 1.0, "unit": "s",
             "vs_baseline": 0},
            {"metric": "bench_run_status", "value": 1.0, "unit": "lines",
             "vs_baseline": 0, "live": True},
        ])
        write("r02_b.jsonl", [
            {"metric": "m_x_seconds", "value": 2.0, "unit": "s",
             "vs_baseline": 0},
            # replay: not evidence, must not appear in history
            {"metric": "m_x_seconds", "value": 9.0, "unit": "s",
             "vs_baseline": 0, "cached": True},
        ])
        hist = cs._history()
        assert list(hist) == ["m_x_seconds"]  # run_status + replay excluded
        assert [v for _, v, _, _ in hist["m_x_seconds"]] == [1.0, 2.0]
        # 1.0 -> 2.0 crosses the 1.5x flag threshold.
        (f0, v0, _, _), (f1, v1, _, _) = hist["m_x_seconds"]
        assert v1 / v0 > cs.DELTA_FLAG
