"""ALS + logistic regression tests — algorithm-level coverage the reference
left untested (SURVEY.md §4: ALS and lr have no tests there)."""

import numpy as np
import pytest

from marlin_tpu.matrix.dense import DenseVecMatrix
from marlin_tpu.matrix.sparse import CoordinateMatrix
from marlin_tpu.ml import als_run, predict


def _synthetic_ratings(rng, m=30, n=20, rank=3, density=0.5):
    u_true = rng.standard_normal((m, rank))
    p_true = rng.standard_normal((n, rank))
    full = u_true @ p_true.T
    mask = rng.random((m, n)) < density
    ui, pj = np.nonzero(mask)
    return CoordinateMatrix(ui, pj, full[ui, pj].astype(np.float32), shape=(m, n)), full, mask


class TestALS:
    def test_reconstructs_observed_ratings(self, rng):
        ratings, full, mask = _synthetic_ratings(rng)
        uf, pf = als_run(ratings, rank=3, iterations=12, lambda_=0.05, seed=1)
        ui, pj = np.nonzero(mask)
        pred = predict(uf, pf, ui, pj)
        rmse = np.sqrt(np.mean((pred - full[ui, pj]) ** 2))
        assert rmse < 0.2, f"ALS failed to fit observed ratings, rmse={rmse}"

    def test_output_shapes_and_types(self, rng):
        ratings, _, _ = _synthetic_ratings(rng, m=12, n=9)
        uf, pf = als_run(ratings, rank=4, iterations=2, seed=2)
        assert isinstance(uf, DenseVecMatrix) and isinstance(pf, DenseVecMatrix)
        assert uf.shape == (12, 4) and pf.shape == (9, 4)

    def test_cold_entities_get_zero_factors(self):
        # User 2 and product 3 have no ratings -> solvable identity system.
        cm = CoordinateMatrix([0, 1], [0, 1], np.array([3.0, 4.0], np.float32), shape=(3, 4))
        uf, pf = als_run(cm, rank=2, iterations=3, seed=0)
        np.testing.assert_allclose(uf.to_numpy()[2], 0.0, atol=1e-6)
        np.testing.assert_allclose(pf.to_numpy()[3], 0.0, atol=1e-6)

    def test_implicit_mode_ranks_positives_higher(self, rng):
        # Implicit feedback: observed cells should score above unobserved.
        ratings, full, mask = _synthetic_ratings(rng, density=0.4)
        binary = CoordinateMatrix(
            *np.nonzero(mask),
            np.ones(mask.sum(), np.float32),
            shape=mask.shape,
        )
        uf, pf = als_run(
            binary, rank=3, iterations=10, lambda_=0.05, implicit_prefs=True,
            alpha=10.0, seed=3,
        )
        scores = uf.to_numpy() @ pf.to_numpy().T
        assert scores[mask].mean() > scores[~mask].mean() + 0.2

    def test_als_entry_point_on_coordinate_matrix(self, rng):
        ratings, _, _ = _synthetic_ratings(rng, m=10, n=8)
        uf, pf = ratings.als(rank=2, iterations=2, seed=4)
        assert uf.shape == (10, 2) and pf.shape == (8, 2)


class TestLogisticRegression:
    def test_separable_data(self, rng):
        # Rows are (label, features) with the label column becoming the
        # intercept, matching the reference's lr contract.
        m, d = 200, 3
        x = rng.standard_normal((m, d))
        w_true = np.array([1.5, -2.0, 0.5])
        labels = (x @ w_true + 0.2 > 0).astype(float)
        data = np.hstack([labels[:, None], x])
        w = DenseVecMatrix(data).lr(step_size=5.0, iters=300)
        assert w.shape == (d + 1,)
        # Predictions from learned weights (first weight is the intercept).
        z = w[0] + x @ w[1:]
        acc = ((z > 0).astype(float) == labels).mean()
        assert acc > 0.95, f"lr accuracy {acc}"
