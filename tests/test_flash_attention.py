"""Pallas flash attention vs the NumPy oracle (interpret mode on the CPU mesh).

Golden-value pattern of the reference suite: kernel output vs a hand-computed
oracle (LocalMatrixSuite.scala:8-72 style), plus composition with the
all-to-all sequence-parallel engine.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marlin_tpu.ops.flash_attention import flash_attention


def oracle(q, k, v, scale=None, causal=False):
    q, k, v = (np.asarray(x, np.float64) for x in (q, k, v))
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    logits = scale * (q @ k.T)
    if causal:
        mask = np.arange(k.shape[0])[None, :] <= np.arange(q.shape[0])[:, None]
        logits = np.where(mask, logits, -np.inf)
    logits -= logits.max(axis=1, keepdims=True)
    p = np.exp(logits)
    return (p / p.sum(axis=1, keepdims=True)) @ v


def oracle_mh(q, k, v, **kw):
    return np.stack(
        [oracle(q[:, h], k[:, h], v[:, h], **kw) for h in range(q.shape[1])], axis=1
    )


def rand(seed, *shape):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32)


class TestFlashAttention:
    def test_single_head_full(self):
        q, k, v = rand(0, 64, 32), rand(1, 64, 32), rand(2, 64, 32)
        out = flash_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), oracle(q, k, v), rtol=2e-5, atol=2e-5)

    def test_causal(self):
        q, k, v = rand(3, 48, 16), rand(4, 48, 16), rand(5, 48, 16)
        out = flash_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), oracle(q, k, v, causal=True), rtol=2e-5, atol=2e-5
        )

    def test_cross_attention_lengths(self):
        q, k, v = rand(6, 40, 24), rand(7, 72, 24), rand(8, 72, 24)
        out = flash_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), oracle(q, k, v), rtol=2e-5, atol=2e-5)

    def test_multihead(self):
        q, k, v = rand(9, 32, 4, 16), rand(10, 32, 4, 16), rand(11, 32, 4, 16)
        out = flash_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), oracle_mh(q, k, v, causal=True), rtol=2e-5, atol=2e-5
        )

    def test_unaligned_lengths_and_dim(self):
        # Neither S (113/37) nor D (19) aligned to tiles: exercises padding.
        q, k, v = rand(12, 113, 19), rand(13, 37, 19), rand(14, 37, 19)
        out = flash_attention(q, k, v, block_q=32, block_k=32)
        np.testing.assert_allclose(np.asarray(out), oracle(q, k, v), rtol=2e-5, atol=2e-5)

    def test_multiple_kv_blocks_online_merge(self):
        # Force several k blocks so the running-max/denominator merge runs.
        q, k, v = rand(15, 64, 8), rand(16, 256, 8), rand(17, 256, 8)
        out = flash_attention(q, k, v, block_q=32, block_k=32)
        np.testing.assert_allclose(np.asarray(out), oracle(q, k, v), rtol=2e-5, atol=2e-5)

    def test_custom_scale(self):
        q, k, v = rand(18, 32, 8), rand(19, 32, 8), rand(20, 32, 8)
        out = flash_attention(q, k, v, scale=0.25)
        np.testing.assert_allclose(
            np.asarray(out), oracle(q, k, v, scale=0.25), rtol=2e-5, atol=2e-5
        )

    def test_matches_xla_attend_bitwise_shape(self):
        q, k, v = rand(21, 32, 8), rand(22, 32, 8), rand(23, 32, 8)
        assert flash_attention(q, k, v).shape == (32, 8)
        qh = rand(24, 32, 2, 8)
        assert flash_attention(qh, qh, qh).shape == (32, 2, 8)


class TestUlyssesFlashComposition:
    def test_flash_local_kernel_under_shard_map(self, mesh):
        from marlin_tpu.parallel import ulysses_self_attention

        q, k, v = (rand(s, 32, 8, 16).astype(jnp.float64) for s in (25, 26, 27))
        out = ulysses_self_attention(q, k, v, mesh=mesh, local_kernel="flash")
        want = ulysses_self_attention(q, k, v, mesh=mesh, local_kernel="xla")
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(want), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(out), oracle_mh(q, k, v), rtol=1e-5, atol=1e-5
        )

    def test_flash_causal_under_shard_map(self, mesh):
        from marlin_tpu.parallel import ulysses_self_attention

        q, k, v = (rand(s, 32, 8, 16).astype(jnp.float64) for s in (28, 29, 30))
        out = ulysses_self_attention(
            q, k, v, mesh=mesh, causal=True, local_kernel="flash"
        )
        np.testing.assert_allclose(
            np.asarray(out), oracle_mh(q, k, v, causal=True), rtol=1e-5, atol=1e-5
        )

    def test_bad_kernel_name(self, mesh):
        from marlin_tpu.parallel import ulysses_self_attention

        q = rand(31, 32, 8, 16)
        with pytest.raises(ValueError, match="local_kernel"):
            ulysses_self_attention(q, q, q, mesh=mesh, local_kernel="mxu")


class TestWideV:
    def test_v_head_dim_differs(self):
        # head_dim_v != head_dim: v pads to a different lane multiple.
        q, k = rand(32, 48, 24), rand(33, 48, 24)
        v = rand(34, 48, 40)
        out = flash_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), oracle(q, k, v), rtol=2e-5, atol=2e-5)
        assert out.shape == (48, 40)

    def test_v_wider_than_lane_tile(self):
        q, k = rand(35, 32, 128), rand(36, 32, 128)
        v = rand(37, 32, 192)
        out = flash_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), oracle(q, k, v), rtol=2e-5, atol=2e-5)
        assert out.shape == (32, 192)

    def test_qk_dim_mismatch_rejected(self):
        q, k, v = rand(38, 32, 16), rand(39, 32, 24), rand(40, 32, 16)
        with pytest.raises(ValueError, match="head_dim"):
            flash_attention(q, k, v)


class TestGradients:
    def test_grads_match_xla_oracle(self, rng):
        # custom VJP (backward = f32 recompute) vs autodiff through the XLA
        # softmax-attention oracle.
        s, h, d = 64, 2, 32
        q, k, v = (jnp.asarray(rng.standard_normal((s, h, d)), jnp.float32)
                   for _ in range(3))

        def flash_loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

        def oracle_loss(q, k, v):
            scale = 1.0 / np.sqrt(d)
            logits = jnp.einsum("shd,thd->hst", q, k) * scale
            mask = jnp.arange(s)[None, :] <= jnp.arange(s)[:, None]
            logits = jnp.where(mask[None], logits, -1e30)
            out = jnp.einsum("hst,thd->shd", jax.nn.softmax(logits, -1), v)
            return jnp.sum(out ** 2)

        gf = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        go = jax.grad(oracle_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, go):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_grad_noncausal_cross_length(self, rng):
        sq, skv, h, d = 32, 48, 2, 16
        q = jnp.asarray(rng.standard_normal((sq, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((skv, h, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((skv, h, d)), jnp.float32)

        def flash_loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v) ** 2)

        def oracle_loss(q, k, v):
            scale = 1.0 / np.sqrt(d)
            logits = jnp.einsum("shd,thd->hst", q, k) * scale
            out = jnp.einsum("hst,thd->shd", jax.nn.softmax(logits, -1), v)
            return jnp.sum(out ** 2)

        gf = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        go = jax.grad(oracle_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, go):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)


class TestGQA:
    """Grouped-query attention: Hk < H via index-map grouping."""

    def _oracle(self, q, k, v, causal=False):
        # Broadcast K/V heads to the full count and run plain attention.
        import numpy as np

        g = q.shape[1] // k.shape[1]
        kf = np.repeat(np.asarray(k, np.float64), g, axis=1)
        vf = np.repeat(np.asarray(v, np.float64), g, axis=1)
        qf = np.asarray(q, np.float64)
        logits = np.einsum("shd,thd->hst", qf, kf) / np.sqrt(q.shape[-1])
        if causal:
            m = np.arange(k.shape[0])[None, :] <= np.arange(q.shape[0])[:, None]
            logits = np.where(m[None], logits, -1e30)
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        return np.einsum("hst,thd->shd", p, vf)

    def test_gqa_matches_broadcast_oracle(self, rng):
        import numpy as np

        for hk, causal in [(2, False), (2, True), (1, False)]:  # GQA + MQA
            q = jnp.asarray(rng.standard_normal((192, 4, 32)), jnp.float32)
            k = jnp.asarray(rng.standard_normal((192, hk, 32)), jnp.float32)
            v = jnp.asarray(rng.standard_normal((192, hk, 32)), jnp.float32)
            got = np.asarray(flash_attention(q, k, v, causal=causal))
            ref = self._oracle(q, k, v, causal)
            np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)

    def test_gqa_grad_matches_broadcast_model(self, rng):
        # d/dk of GQA == sum over the group of the broadcast model's d/dk.
        import numpy as np

        q = jnp.asarray(rng.standard_normal((48, 4, 16)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((48, 2, 16)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((48, 2, 16)), jnp.float32)

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        def loss_bcast(q, kb, vb):
            return jnp.sum(flash_attention(q, kb, vb, causal=True) ** 2)

        kb = jnp.repeat(k, 2, axis=1)
        vb = jnp.repeat(v, 2, axis=1)
        gqb, gkb, gvb = jax.grad(loss_bcast, argnums=(0, 1, 2))(q, kb, vb)
        np.testing.assert_allclose(np.asarray(gq), np.asarray(gqb),
                                   rtol=1e-4, atol=1e-5)
        # Broadcast-model K/V grads per group sum back to the GQA grads.
        np.testing.assert_allclose(
            np.asarray(gk),
            np.asarray(gkb).reshape(48, 2, 2, 16).sum(axis=2),
            rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(gv),
            np.asarray(gvb).reshape(48, 2, 2, 16).sum(axis=2),
            rtol=1e-4, atol=1e-5)

    def test_bad_head_ratio_raises(self, rng):
        import pytest

        q = jnp.zeros((16, 4, 8), jnp.float32)
        k = jnp.zeros((16, 3, 8), jnp.float32)
        with pytest.raises(ValueError):
            flash_attention(q, k, k)


class TestSlidingWindow:
    def test_window_matches_banded_oracle_and_grads(self, rng):
        s_len, h, d, w = 200, 2, 32, 48

        def banded(q, k, v):
            qf, kf, vf = (jnp.swapaxes(x, 0, 1).astype(jnp.float32)
                          for x in (q, k, v))
            logits = jnp.einsum("hsd,htd->hst", qf, kf) / np.sqrt(d)
            kp = jnp.arange(s_len)[None, :]
            qp = jnp.arange(s_len)[:, None]
            mask = (kp <= qp) & (kp > qp - w)
            logits = jnp.where(mask[None], logits, -1e30)
            return jnp.einsum(
                "hst,htd->shd", jax.nn.softmax(logits, -1), vf)

        q, k, v = (jnp.asarray(rng.standard_normal((s_len, h, d)),
                               jnp.float32) for _ in range(3))
        got = flash_attention(q, k, v, causal=True, window=w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(banded(q, k, v)),
                                   rtol=2e-5, atol=2e-5)
        g = jax.grad(lambda *a: jnp.sum(
            flash_attention(*a, causal=True, window=w) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: jnp.sum(banded(*a) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        for a_, b_ in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a_), np.asarray(b_),
                                       rtol=1e-3, atol=1e-4)

    def test_window_requires_causal(self, rng):
        q = jnp.zeros((16, 2, 8), jnp.float32)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, q, q, window=4)

    # Two fuzz seeds in tier-1, two under -m slow (ROADMAP 9 budget —
    # each seed is ~5 s of fresh band-config compiles).
    @pytest.mark.parametrize("seed", [
        0, 1, pytest.param(2, marks=pytest.mark.slow),
        pytest.param(3, marks=pytest.mark.slow)])
    def test_window_fuzz_random_band_configs(self, seed):
        # Randomized (S, window, block) fuzz vs the dense banded oracle —
        # band-boundary bugs (clamped-duplicate double counts, off-by-one
        # band edges, pad-tail interactions) live exactly in the corners a
        # fixed-shape test can miss. Forward + all three gradients.
        r = np.random.default_rng(100 + seed)
        s_len = int(r.integers(65, 400))
        w = int(r.integers(1, s_len + 32))
        bq = int(r.choice([32, 64, 128]))
        bk = int(r.choice([32, 64, 128]))
        h, d = 2, 32

        def banded(q, k, v):
            qf, kf, vf = (jnp.swapaxes(x, 0, 1).astype(jnp.float32)
                          for x in (q, k, v))
            logits = jnp.einsum("hsd,htd->hst", qf, kf) / np.sqrt(d)
            kp = jnp.arange(s_len)[None, :]
            qp = jnp.arange(s_len)[:, None]
            mask = (kp <= qp) & (kp > qp - w)
            logits = jnp.where(mask[None], logits, -1e30)
            return jnp.einsum("hst,htd->shd", jax.nn.softmax(logits, -1), vf)

        q, k, v = (jnp.asarray(r.standard_normal((s_len, h, d)),
                               jnp.float32) for _ in range(3))
        args = dict(causal=True, window=w, block_q=bq, block_k=bk)
        got = flash_attention(q, k, v, **args)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(banded(q, k, v)),
            rtol=3e-5, atol=3e-5, err_msg=f"fwd s={s_len} w={w} bq={bq} bk={bk}")
        g = jax.grad(lambda *a: jnp.sum(
            flash_attention(*a, **args) ** 2), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: jnp.sum(banded(*a) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        for name, a_, b_ in zip("q k v".split(), g, gr):
            np.testing.assert_allclose(
                np.asarray(a_), np.asarray(b_), rtol=2e-3, atol=3e-4,
                err_msg=f"d{name} s={s_len} w={w} bq={bq} bk={bk}")

    def test_window_grads_multiblock_no_double_count(self, rng):
        # Regression (r03 review): the dK/dV kernel's shrunk q sweep can
        # overrun n_q; the clamped duplicate of the LAST q-block is MORE
        # causal-valid (unlike the forward's k overrun, which is dead past
        # the diagonal) and was re-accumulated into dk/dv — ~7% error
        # concentrated in the trailing k-blocks. Needs multiple blocks AND
        # an overrunning sweep, which the small single-block shapes above
        # never hit: S=512 with 128-blocks and window=128 sweeps
        # lo_q(n_k-1) + ii past n_q.
        s_len, h, d, w = 512, 2, 64, 128

        def banded(q, k, v):
            qf, kf, vf = (jnp.swapaxes(x, 0, 1).astype(jnp.float32)
                          for x in (q, k, v))
            logits = jnp.einsum("hsd,htd->hst", qf, kf) / np.sqrt(d)
            kp = jnp.arange(s_len)[None, :]
            qp = jnp.arange(s_len)[:, None]
            mask = (kp <= qp) & (kp > qp - w)
            logits = jnp.where(mask[None], logits, -1e30)
            return jnp.einsum("hst,htd->shd", jax.nn.softmax(logits, -1), vf)

        q, k, v = (jnp.asarray(rng.standard_normal((s_len, h, d)),
                               jnp.float32) for _ in range(3))
        args = dict(causal=True, window=w, block_q=128, block_k=128)
        g = jax.grad(lambda *a: jnp.sum(
            flash_attention(*a, **args) ** 2), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: jnp.sum(banded(*a) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        for name, a_, b_ in zip("q k v".split(), g, gr):
            np.testing.assert_allclose(
                np.asarray(a_), np.asarray(b_), rtol=2e-3, atol=2e-4,
                err_msg=f"d{name}")

    @pytest.mark.parametrize("bq,bk,w,n", [
        (128, 128, 128, 16), (128, 64, 96, 9), (64, 128, 200, 7),
        (256, 128, 512, 32), (96, 96, 100, 5), (128, 128, 1, 4),
    ])
    def test_shrunk_sweep_covers_every_live_block(self, bq, bk, w, n):
        # The windowed grid shrink (HBM reads ~ S*window) must never drop
        # a live (i, j) pair: for every q-block i, all k-blocks passing
        # _block_live lie inside [lo_k(i), lo_k(i) + nb_w); dually for the
        # dK/dV kernel's q sweep.
        from marlin_tpu.ops.flash_attention import (
            _block_live, _win_kblocks, _win_lo_k, _win_lo_q, _win_qblocks)

        nb_w = _win_kblocks(n, block_q=bq, block_k=bk, window=w)
        nb_q = _win_qblocks(n, block_q=bq, block_k=bk, window=w)
        for i in range(n):
            lo = int(_win_lo_k(i, block_q=bq, block_k=bk, window=w))
            for j in range(n):
                if bool(_block_live(i, j, causal=True, block_q=bq,
                                    block_k=bk, window=w)):
                    assert lo <= j < lo + nb_w, (i, j, lo, nb_w)
        for j in range(n):
            lo = int(_win_lo_q(j, block_q=bq, block_k=bk, window=w))
            for i in range(n):
                if bool(_block_live(i, j, causal=True, block_q=bq,
                                    block_k=bk, window=w)):
                    assert lo <= i < lo + nb_q, (j, i, lo, nb_q)


class TestFlashBackwardKernels:
    """The Pallas flash backward (dQ + dK/dV kernels, probability tiles
    recomputed from the saved logsumexp) must match the XLA closed-form
    softmax-attention gradients on every mask configuration. No (Sq, Skv)
    buffer exists in the Pallas path — training memory is S*D."""

    @staticmethod
    def _dense_attn(q, k, v, causal, window):
        """The one shared dense closed-form oracle (already head-matched)."""
        qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
        sc = jnp.float32(1.0 / np.sqrt(q.shape[-1]))  # keep f32 under x64
        logits = jnp.einsum("shd,thd->hst", qf, kf) * sc
        if causal:
            kp = jnp.arange(k.shape[0])[None, :]
            qp = jnp.arange(q.shape[0])[:, None]
            m = kp <= qp
            if window:
                m = jnp.logical_and(m, kp > qp - window)
            logits = jnp.where(m[None], logits, -1e30)
        return jnp.einsum("hst,thd->shd", jax.nn.softmax(logits, -1), vf)

    @classmethod
    def _xla_grads(cls, q, k, v, g, causal, window):
        return jax.vjp(
            lambda q, k, v: cls._dense_attn(q, k, v, causal, window),
            q, k, v,
        )[1](g.astype(jnp.float32))

    @pytest.mark.parametrize(
        "sq,skv,h,d,dv,causal,window",
        [
            (96, 96, 2, 32, 32, False, 0),
            (96, 96, 2, 32, 32, True, 0),
            (96, 96, 2, 32, 32, True, 24),   # sliding window band
            (80, 112, 2, 32, 48, False, 0),  # cross lengths + dv != d
            (90, 100, 2, 32, 32, True, 0),   # pad in both seq dims
            (50, 50, 1, 16, 16, True, 16),   # window + pad + single head
        ],
    )
    def test_grads_match_xla_closed_form(self, rng, sq, skv, h, d, dv,
                                         causal, window):
        q = jnp.asarray(rng.standard_normal((sq, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((skv, h, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((skv, h, dv)), jnp.float32)
        g = jnp.asarray(rng.standard_normal((sq, h, dv)), jnp.float32)
        _, vjp = jax.vjp(
            lambda q, k, v: flash_attention(
                q, k, v, causal=causal, window=window,
                block_q=32, block_k=32, interpret=True),
            q, k, v,
        )
        got = vjp(g)
        ref = self._xla_grads(q, k, v, g, causal, window)
        for name, a, b in zip(("dq", "dk", "dv"), got, ref):
            err = float(jnp.max(jnp.abs(a - b))
                        / (jnp.max(jnp.abs(b)) + 1e-30))
            assert err < 2e-5, (name, err)

    @classmethod
    def _xla_grads_gqa(cls, q, k, v, g, causal, window):
        group = q.shape[1] // k.shape[1]

        def f(q, k, v):  # broadcast K/V heads; vjp sums grads per kv-head
            return cls._dense_attn(q, jnp.repeat(k, group, axis=1),
                                   jnp.repeat(v, group, axis=1),
                                   causal, window)

        return jax.vjp(f, q, k, v)[1](g.astype(jnp.float32))

    @pytest.mark.parametrize(
        "heads,kv_heads,causal,window",
        [(4, 2, True, 0), (4, 1, False, 0), (4, 2, True, 24)],
    )
    def test_gqa_grads_match_dense_oracle(self, rng, heads, kv_heads,
                                          causal, window):
        sq = 96
        q = jnp.asarray(rng.standard_normal((sq, heads, 32)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((sq, kv_heads, 32)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((sq, kv_heads, 32)), jnp.float32)
        g = jnp.asarray(rng.standard_normal((sq, heads, 32)), jnp.float32)
        _, vjp = jax.vjp(
            lambda q, k, v: flash_attention(
                q, k, v, causal=causal, window=window, interpret=True,
                block_q=32, block_k=32),
            q, k, v,
        )
        got = vjp(g)
        ref = self._xla_grads_gqa(q, k, v, g, causal, window)
        for name, a, b in zip(("dq", "dk", "dv"), got, ref):
            err = float(jnp.max(jnp.abs(a - b))
                        / (jnp.max(jnp.abs(b)) + 1e-30))
            assert err < 2e-5, (name, err)

    @pytest.mark.parametrize("kv_heads", [2, 1])  # MHA and MQA/GQA
    def test_no_s_squared_buffer_in_jaxpr(self, rng, kv_heads):
        # Neither backward path may materialize an (Sq, Skv) array: check
        # no intermediate in the vjp jaxpr has both seq dims (recursing
        # into nested jaxprs).
        sq = skv = 256
        q = jnp.asarray(rng.standard_normal((sq, 2, 32)), jnp.float32)
        kv = jnp.asarray(
            rng.standard_normal((skv, kv_heads, 32)), jnp.float32)

        def loss(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=True, block_q=64, block_k=64,
                interpret=True))

        jx = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, kv, kv)
        bad = []

        def scan(jaxpr):  # recurse into jit/scan/cond sub-jaxprs
            for eqn in jaxpr.eqns:
                for v in eqn.outvars:
                    shape = getattr(v.aval, "shape", None)
                    if shape and sum(dim == sq for dim in shape) >= 2:
                        bad.append(shape)
                for p in eqn.params.values():
                    if hasattr(p, "jaxpr"):
                        scan(p.jaxpr)
                    elif isinstance(p, (list, tuple)):
                        for pp in p:
                            if hasattr(pp, "jaxpr"):
                                scan(pp.jaxpr)

        scan(jx.jaxpr)
        assert not bad, f"S^2 intermediates present: {bad}"
